"""AST -> JAX lowering: compile reference actions to device kernels
(SURVEY.md §7.4 `lower/`; VERDICT r4 "what's missing" item 1).

The hand kernels (models/*_kernel.py) prove the dense layout and the
engine contract; this module generates the per-action guard/action
functions FROM THE PARSED SPEC instead of by hand.  The pipeline:

    frontend AST --ir.extract_action--> lane binders + conjunct tree
                 --Lowerer------------> (guard_fn, action_fn) closures
                                        over the dense state

Design decisions (and their honesty boundaries):

* The dense LAYOUT stays declared per spec family (the codec classes in
  models/): the compiler consumes it, it does not yet synthesize one.
  What is generated from the AST: every guard, every state mutation,
  the lane binders, the lane->replica map for incremental
  fingerprinting, and the invariant kernels.
* The message-algebra combinators ``SendFunc``/``BroadcastFunc``/
  ``DiscardFunc`` (A01:152-169 — identical in every corpus module) are
  intrinsics lowered to the kernel base's bag primitives (`_bag_send`,
  `_broadcast`, `_bag_discard`).  Their *wrappers* (Send, SendOnce,
  Broadcast, Discard, DiscardAndSend, DiscardAndBroadcast,
  SendAsReceived) are NOT special-cased: they inline from their spec
  definitions like any other operator, which also surfaces their
  embedded guards (``messages[d] > 0``, A01:189) as compiled guard
  conjuncts.
* Evaluation is eager with clipped indexing (the §2.7.1 lazy-semantics
  hazard is neutralized by masking, exactly as in the hand kernels).
* ``CHOOSE m \\in DOMAIN messages : P(m)`` lowers to a vectorized
  candidate mask + deterministic lexicographic tie-break on the record
  columns in ``value_key`` field order (alphabetical), matching the
  interpreter's deterministic CHOOSE (core/values.py:169-195).
* Inner quantifiers over the bag/dynamic ranges vectorize onto fresh
  broadcast axes (var at nesting depth d -> axis -(d+1)); quantifiers
  over static sets unroll.
* A disjunction of primed branches (SendDVC's SendAsReceived/Send
  split, A01:493-496; ReceiveSV's IF, A01:631-637) compiles both
  branches and selects elementwise; branches must be guard-exclusive,
  which every corpus action satisfies.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.values import ModelValue, TLAError, tla_eq
from ..frontend.tla_ast import Def
from .ir import (D_INTRANGE, D_MSGS, D_REPLICAS, D_SUBSETS, D_TRACKER,
                 D_VALUES, contains_prime, extract_action)

I32 = jnp.int32
INF = jnp.int32(0x7FFFFFFF)

# header column layout (models/vsr.py)
from ..models.vsr import (H_COMMIT, H_DEST, H_FIRST, H_LNV, H_OP,  # noqa: E402
                          H_SRC, H_TYPE, H_VIEW, H_X)

# message record field -> (header column, value space)
MSG_FIELD_COLS = {
    "type": (H_TYPE, "mtype"),
    "view_number": (H_VIEW, None),
    "op_number": (H_OP, None),
    "commit_number": (H_COMMIT, None),
    "dest": (H_DEST, "replica"),
    "source": (H_SRC, "replica"),
    "last_normal_vn": (H_LNV, None),
    "first_op": (H_FIRST, None),
    "x": (H_X, None),
    "op": (H_OP, None),            # AL05 RecoveryMsg floor
    "prefix_ceil": (H_FIRST, None),  # AL05 response suffix base
}

# per-message-type record fields, for the deterministic-CHOOSE key
# (alphabetical = value_key order for records); log/message expand to
# their plane columns
MSG_TYPE_FIELDS = {
    "PrepareMsg": ("commit_number", "dest", "message", "op_number",
                   "source", "type", "view_number"),
    "PrepareOkMsg": ("dest", "op_number", "source", "type",
                     "view_number"),
    "StartViewChangeMsg": ("dest", "source", "type", "view_number"),
    "DoViewChangeMsg": ("commit_number", "dest", "last_normal_vn",
                        "log", "op_number", "source", "type",
                        "view_number"),
    "StartViewMsg": ("commit_number", "dest", "log", "op_number",
                     "source", "type", "view_number"),
    "GetStateMsg": ("dest", "op_number", "source", "type",
                    "view_number"),
    "NewStateMsg": ("commit_number", "dest", "first_op", "log",
                    "op_number", "source", "type", "view_number"),
    # `op` is AL05's floor field; on RR05 RecoveryMsg rows the column
    # is constant 0, so including it cannot affect a tie-break
    "RecoveryMsg": ("dest", "op", "source", "type", "x"),
    "RecoveryResponseMsg": ("commit_number", "dest", "log",
                            "op_number", "source", "type",
                            "view_number", "x"),
}

# state variable -> dense plane binding for the ST03 layout family
# (kind, plane, space)
VAR_KINDS = {
    "rep_status": ("rep", "status", "status"),
    "rep_view_number": ("rep", "view", None),
    "rep_op_number": ("rep", "op", None),
    "rep_commit_number": ("rep", "commit", None),
    "rep_last_normal_view": ("rep", "lnv", None),
    "rep_sent_svc": ("rep", "sent_svc", "bool"),
    "rep_sent_dvc": ("rep", "sent_dvc", "bool"),
    "rep_sent_sv": ("rep", "sent_sv", "bool"),
    "no_progress": ("rep", "no_prog", "bool"),
    # replog entries carry their LENGTH PLANE in the third slot:
    # Len(rep_log) == rep_op_number and Len(rep_app_state) ==
    # rep_commit_number are layout invariants (models/st03.py, as04.py)
    "rep_log": ("replog", "log", "op"),
    "rep_app_state": ("replog", "app", "commit"),
    "rep_peer_op_number": ("repfn", "peer_op", None),
    "no_progress_ctr": ("glob", "np_ctr", None),
    "aux_svc": ("glob", "aux_svc", None),
    "aux_client_acked": ("auxfn", "aux_acked", None),
    "messages": ("bag", None, None),
    "replicas": ("repset_const", None, None),
    # per-replica record-SET trackers stored in [R, R] source-indexed
    # slot planes (models/i01.py, as04.py, rr05.py)
    "rep_recv_dvc": ("tracker", "rep_recv_dvc", None),
    "rep_rec_recv": ("tracker", "rep_rec_recv", None),
    "rep_rec_number": ("rep", "rec_number", None),
    "aux_restart": ("glob", "aux_restart", None),
}

# tracker variable -> dense schema.  `planes` maps record fields to
# slot planes; `source`/`dest`/`type` are implicit (slot index / row /
# constant); `view_plane` may be absent from a layout (AS04: implied =
# View(dest)); `has_flag` marks layouts whose log/op/commit fields are
# Nil-able (RR05 recovery responses: -1 sentinels + rec_has_log);
# `implied` maps extra fields to the per-replica plane holding their
# implied value (RR05: x = rep_rec_number[dest]).
TRACKER_SCHEMAS = {
    "rep_recv_dvc": {
        "presence": "dvc",
        "type_const": "DoViewChangeMsg",
        "planes": {"last_normal_vn": "dvc_lnv", "op_number": "dvc_op",
                   "commit_number": "dvc_commit"},
        "view_plane": "dvc_view",
        "log": ("dvc_log", "dvc_op"),
        "has_flag": None,
        "implied": {},
        # alphabetical record-field order for deterministic CHOOSE
        "choose_cols": ("commit_number", "last_normal_vn", "log",
                        "op_number", "source", "view_number"),
    },
    "rep_rec_recv": {
        "presence": "rec",
        "type_const": "RecoveryResponseMsg",
        "planes": {"view_number": "rec_view", "op_number": "rec_op",
                   "commit_number": "rec_commit"},
        "view_plane": None,
        "log": ("rec_log", "rec_op"),
        "has_flag": "rec_has_log",
        "implied": {"x": "rec_number"},
        "choose_cols": ("commit_number", "log", "op_number", "source",
                        "view_number"),
    },
}

# module-specific overrides: AL05's recovery responses carry a SUFFIX
# log (log_suffix, 0-based from prefix_ceil+1 = rec_ceil+1) instead of
# a whole log (models/al05.py)
TRACKER_SCHEMAS_BY_MODULE = {
    ("VR_REPLICA_RECOVERY_ASYNC_LOG", "rep_rec_recv"): {
        "presence": "rec",
        "type_const": "RecoveryResponseMsg",
        "planes": {"view_number": "rec_view", "op_number": "rec_op",
                   "commit_number": "rec_commit",
                   "prefix_ceil": "rec_ceil"},
        "view_plane": None,
        "log": ("rec_log", "rec_op"),
        "log_name": "log_suffix",
        "suffix_base": "rec_ceil",
        "has_flag": "rec_has_log",
        "implied": {"x": "rec_number"},
        "choose_cols": ("commit_number", "log", "op_number",
                        "prefix_ceil", "source", "view_number"),
    },
}

_BAG_COMBINATORS = ("SendFunc", "BroadcastFunc", "DiscardFunc")


class LowerError(TLAError):
    pass


# ----------------------------------------------------------------------
# dense values
# ----------------------------------------------------------------------
class DV:
    """A lowered (dense) TLA+ value."""

    def __init__(self, kind, **kw):
        self.kind = kind
        self.__dict__.update(kw)

    def __repr__(self):
        return f"DV({self.kind})"


def d_int(v, space=None):
    return DV("int", v=v, space=space)


def d_bool(v):
    return DV("bool", v=v)


def d_static(v):
    return DV("static", v=v)


def d_log(arr, length, first=1):
    """A log-valued function [first..first+length-1 -> entry]: `arr`
    is the packed-entry row stored 0-based from `first` (the codec's
    m_log/NewState convention, models/st03.py)."""
    return DV("log", arr=arr, length=length, first=first)


def d_msg(k, mask=None, axis=None):
    return DV("msg", k=k, mask=mask, axis=axis)


class Env:
    __slots__ = ("vars", "depth")

    def __init__(self, vars=None, depth=0):
        self.vars = vars or {}
        self.depth = depth

    def bind(self, name, dv):
        nv = dict(self.vars)
        nv[name] = dv
        return Env(nv, self.depth)

    def bind_many(self, d):
        nv = dict(self.vars)
        nv.update(d)
        return Env(nv, self.depth)

    def deeper(self):
        return Env(self.vars, self.depth + 1)


# ----------------------------------------------------------------------
class Lowerer:
    def __init__(self, spec, codec, kern):
        self.spec = spec
        self.codec = codec
        self.kern = kern
        self.module = spec.module
        self.consts = spec.ev.constants
        s = codec.shape
        self.R, self.V, self.M = s.R, s.V, s.MAX_MSGS
        self.MAX_OPS = s.MAX_OPS
        # entry packing: A01-family packs (value_id << bits) | view
        from ..models.a01 import ENTRY_VIEW_BITS, A01Codec
        self.entry_bits = ENTRY_VIEW_BITS if isinstance(codec, A01Codec) \
            else 0
        # stack of inlined-operator argument ASTs (bag-walker resolves
        # `messages`-typed parameters through it)
        self._ast_args = []
        # which dense planes this codec family carries (AS04's DVC
        # tracker elides the view column — implied = View(dest))
        self.planes = set(codec.zero_state().keys())
        # bounded-recursion unroll state (RECURSIVE operators)
        self._rec_depth = {}
        self._rec_cut = set()

    # -- static encodings ----------------------------------------------
    def enc_static(self, v, space):
        if isinstance(v, bool):
            return int(v)
        if isinstance(v, int):
            return v
        if isinstance(v, ModelValue):
            if space == "status":
                return self.codec.status_id[v]
            if space == "mtype":
                return self.codec.mtype_id[v]
            if space == "value":
                return self.codec.value_id[v]
            if space == "replica":
                if v is self.consts.get("Nil"):
                    return 0
                if v is self.consts.get("AnyDest"):
                    from ..models.st03 import ANYDEST
                    return ANYDEST
            if v in self.codec.value_id:
                return self.codec.value_id[v]
            if v in self.codec.status_id:
                return self.codec.status_id[v]
            if v in self.codec.mtype_id:
                return self.codec.mtype_id[v]
            # NOTE: no bare-Nil fallthrough — a Nil outside a replica-
            # valued field has no universal sentinel (logs use -1
            # lengths, int fields -1), so it must be handled in
            # context (_select) or fail loud here
        raise LowerError(f"cannot encode static {v!r} in space {space}")

    def pack_entry(self, rec, env, st):
        """Log-entry record DV -> packed int."""
        f = rec.fields
        op = f.get("operation")
        vid = self.as_int(op, space="value")
        if self.entry_bits:
            view = self.as_int(f.get("view_number"))
            return (self._j(vid) << self.entry_bits) | self._j(view)
        return vid

    def unpack_entry(self, code, field):
        if self.entry_bits:
            if field == "operation":
                return d_int(self._j(code) >> self.entry_bits,
                             space="value")
            if field == "view_number":
                return d_int(self._j(code)
                             & ((1 << self.entry_bits) - 1))
            if field == "client_id":
                return d_static(self.consts.get("Nil"))
        else:
            if field == "operation":
                return d_int(code, space="value")
        raise LowerError(f"entry field {field} not in packing")

    @staticmethod
    def _j(x):
        return jnp.asarray(x, I32) if not isinstance(x, int) else x

    # -- coercions ------------------------------------------------------
    def as_int(self, dv, space=None):
        if dv.kind == "int":
            return dv.v
        if dv.kind == "bool":
            return jnp.asarray(dv.v, I32) \
                if not isinstance(dv.v, bool) else int(dv.v)
        if dv.kind == "static":
            return self.enc_static(dv.v, space)
        if dv.kind == "entry":
            return dv.v
        raise LowerError(f"not an int: {dv}")

    def as_bool(self, dv):
        if dv.kind == "bool":
            return dv.v
        if dv.kind == "static":
            if isinstance(dv.v, bool):
                return dv.v
        raise LowerError(f"not a bool: {dv}")

    # ==================================================================
    # expression compilation
    # ==================================================================
    def expr(self, e, env, st):
        tag = e[0]
        m = getattr(self, f"_e_{tag}", None)
        if m is None:
            raise LowerError(f"cannot lower expression tag {tag!r}")
        return m(e, env, st)

    # -- leaves ---------------------------------------------------------
    def _e_num(self, e, env, st):
        return d_static(e[1])

    def _e_str(self, e, env, st):
        return d_static(e[1])

    def _e_bool(self, e, env, st):
        return d_static(e[1])

    def _e_at(self, e, env, st):
        return env.vars["@"]

    def _e_id(self, e, env, st):
        name = e[1]
        if name in env.vars:
            return env.vars[name]
        vk = VAR_KINDS.get(name)
        if vk is not None and name in self.module.variables:
            kind, plane, space = vk
            if kind == "glob":
                return d_int(st[plane], space=space)
            if kind == "repset_const":
                return d_static(frozenset(range(1, self.R + 1)))
            if kind == "bag":
                return DV("bag")
            if kind == "auxfn":
                return DV("auxfn")
            return DV("statevar", var=name, kind2=kind, plane=plane,
                      space=space)
        if name in self.consts:
            return d_static(self.consts[name])
        d = self.module.defs.get(name)
        if d is not None:
            if d.params:
                return DV("opdef", d=d, env=env)
            return self.expr(d.body, env, st)
        raise LowerError(f"unbound identifier {name}")

    def _e_call(self, e, env, st):
        _, name, args = e
        if name == "Len":
            lg = self.expr(args[0], env, st)
            return d_int(self._loglen(lg))
        if name == "Append":
            lg = self._as_log(self.expr(args[0], env, st))
            if not (isinstance(lg.first, int) and lg.first == 1):
                raise LowerError("Append to a log slice (first != 1)")
            ent = self.expr(args[1], env, st)
            code = self._entry_code(ent, env, st)
            pos = jnp.clip(self._j(lg.length), 0, self.MAX_OPS - 1)
            return d_log(jnp.asarray(lg.arr, I32).at[pos].set(code),
                         self._j(lg.length) + 1)
        if name == "Cardinality":
            s = self.expr(args[0], env, st)
            if s.kind == "repmask":
                return d_int(s.bits.sum())
            if s.kind == "trackrow":
                pres = self._schema(s.schema)["presence"]
                return d_int(st[pres][s.i].sum())
            if s.kind == "static" and isinstance(s.v, frozenset):
                return d_static(len(s.v))
            elems = self._set_elements(s)
            if elems is not None:
                n = 0
                for _el, msk in elems:
                    n = self._j(n) + (jnp.asarray(msk, I32)
                                      if msk is not None else 1)
                return d_int(n)
            raise LowerError("Cardinality of non-enumerable set")
        if name == "Quantify":
            return self._quantify(args[0], args[1], env, st)
        if name in _BAG_COMBINATORS:
            raise LowerError(
                f"{name} outside a messages' update is unsupported")
        # user operator: inline with evaluated arguments.  LET-defined
        # operators resolve through env.vars and must inline in their
        # CAPTURED env (lexical scoping), not the caller's
        d = self.module.defs.get(name)
        defenv = env
        if d is None and name in env.vars \
                and env.vars[name].kind == "opdef":
            od = env.vars[name]
            d, defenv = od.d, od.env
        if d is None:
            raise LowerError(f"unknown operator {name}")
        vals = [self.expr(a, env, st) for a in args]
        inner = defenv.bind_many(dict(zip(d.params, vals)))
        self._ast_args.append(dict(zip(d.params, args)))
        try:
            return self._e_call_body(name, d, inner, st)
        finally:
            self._ast_args.pop()

    def _e_call_body(self, name, d, inner, st):
        if getattr(d, "recursive", False):
            # bounded unroll with a cutoff that forces the base IF-arm
            # (see _e_if).  The prune is only sound for counter-stepped
            # recursion whose span the layout bounds (log positions are
            # clipped to MAX_OPS) — verify the SHAPE at least: some
            # parameter must step by +-1 in the recursive call and be
            # referenced by a stopping condition, else fail loud
            # instead of silently truncating an unbounded recursion.
            self._check_counter_recursion(name, d)
            depth = self._rec_depth.get(name, 0)
            if depth == 0:
                # the entry call's own frame is innermost; its arg ASTs
                # come from the CALLER's scope, so resolve one frame up
                saved = self._ast_args
                frame = saved[-1]
                self._ast_args = saved[:-1]
                try:
                    self._check_recursion_bound(
                        name, d, [frame.get(p) for p in d.params])
                finally:
                    self._ast_args = saved
            if depth > self.MAX_OPS + 2:
                raise LowerError(
                    f"recursion in {name} exceeded the unroll bound")
            self._rec_depth[name] = depth + 1
            if depth == self.MAX_OPS + 1:
                self._rec_cut.add(name)
            try:
                return self.expr(d.body, inner, st)
            finally:
                self._rec_depth[name] = depth
                self._rec_cut.discard(name)
        return self.expr(d.body, inner, st)


    def _bounded_int_ast(self, e):
        """Is this integer expression STRUCTURALLY bounded by the log
        layout (<= MAX_OPS)?  True for op/commit plane reads
        (rep_op_number[..], rep_commit_number[..]), message/tracker
        op/commit/ceil fields, MinVal over one bounded arg, and +/- of
        a bounded term with a literal.  The unroll/lane bounds derived
        from MAX_OPS are only sound for such expressions — anything
        else must fail loud, not truncate silently."""
        if not isinstance(e, tuple):
            return False
        if e[0] == "apply" and e[1][0] == "id":
            vk = VAR_KINDS.get(e[1][1])
            return bool(vk and vk[0] == "rep"
                        and vk[1] in ("op", "commit"))
        if e[0] == "dot":
            return e[2] in ("op_number", "commit_number",
                            "prefix_ceil", "op")
        if e[0] == "call" and e[1] == "MinVal":
            return any(self._bounded_int_ast(a) for a in e[2])
        if e[0] == "call":
            # e.g. HighestCommitNumber(r): LET m == CHOOSE ... IN
            # m.commit_number — recurse into the definition body (the
            # bounded-ness of a field/plane read is name-local)
            dd = self.module.defs.get(e[1])
            if dd is not None:
                return self._bounded_int_ast(dd.body)
            return False
        if e[0] == "let":
            return self._bounded_int_ast(e[2])
        if e[0] == "if":
            return (self._bounded_int_ast(e[2])
                    and self._bounded_int_ast(e[3]))
        if e[0] == "binop" and e[1] in ("plus", "minus") \
                and e[3][0] == "num":
            return self._bounded_int_ast(e[2])
        if e[0] == "num":
            return e[1] <= self.MAX_OPS
        if e[0] == "id":
            return self._bounded_id(e[1], len(self._ast_args))
        return False

    def _bounded_id(self, name, upto):
        """Resolve a name through inlined-call / LET argument AST
        frames — a frame's values come from the CALLER's scope, so
        resolution continues strictly in outer frames — then module
        defs; conservative (False) when opaque."""
        for i in range(upto - 1, -1, -1):
            frame = self._ast_args[i]
            if name in frame:
                e = frame[name]
                if isinstance(e, tuple) and e[0] == "id":
                    return self._bounded_id(e[1], i)
                saved = self._ast_args
                self._ast_args = saved[:i]
                try:
                    return self._bounded_int_ast(e)
                finally:
                    self._ast_args = saved
        dd = self.module.defs.get(name)
        if dd is not None and not dd.params:
            return self._bounded_int_ast(dd.body)
        return False

    def _check_counter_recursion(self, name, d):
        """Structural soundness check for the bounded unroll: the
        recursive self-call must step some parameter by +-1 (the
        counter), giving the IF cutoff a data-bounded span.  Memoized
        per operator."""
        ok = getattr(self, "_rec_shape_ok", None)
        if ok is None:
            ok = self._rec_shape_ok = {}
        if name in ok:
            if not ok[name]:
                raise LowerError(
                    f"RECURSIVE {name} is not counter-stepped "
                    f"recursion; bounded unroll would be unsound")
            return

        calls = []

        def find(e):
            if not isinstance(e, tuple):
                return
            if e[0] == "call" and e[1] == name:
                calls.append(e[2])
            for x in e:
                if isinstance(x, tuple):
                    find(x)
                elif isinstance(x, list):
                    for y in x:
                        if isinstance(y, tuple):
                            find(y)
        find(d.body)

        def stepped(arg, param):
            return (isinstance(arg, tuple) and arg[0] == "binop"
                    and arg[1] in ("plus", "minus")
                    and arg[2] == ("id", param)
                    and arg[3] == ("num", 1))

        good = bool(calls) and all(
            any(stepped(a, p) for a, p in zip(cargs, d.params))
            for cargs in calls)
        ok[name] = good
        if not good:
            raise LowerError(
                f"RECURSIVE {name} is not counter-stepped recursion; "
                f"bounded unroll would be unsound")

    def _check_recursion_bound(self, name, d, args):
        """At the recursion's entry call, the STOP-bound argument (the
        parameter the body's IF compares the stepped counter against)
        must be structurally layout-bounded — otherwise the MAX_OPS
        unroll would silently truncate.  Resolves argument names
        through the inline-frame stack."""
        body = d.body
        while isinstance(body, tuple) and body[0] == "let":
            body = body[2]
        if not (isinstance(body, tuple) and body[0] == "if"):
            raise LowerError(
                f"RECURSIVE {name}: cutoff needs a top-level IF")
        cond = body[1]
        # the stepped params (p +- 1 in self-calls) are the counters;
        # the OTHER cond side is the stop bound
        calls = []

        def find(e):
            if not isinstance(e, tuple):
                return
            if e[0] == "call" and e[1] == name:
                calls.append(e[2])
            for x in e:
                if isinstance(x, tuple):
                    find(x)
                elif isinstance(x, list):
                    for y in x:
                        if isinstance(y, tuple):
                            find(y)
        find(d.body)
        steppedp = set()
        for cargs in calls:
            for a, p in zip(cargs, d.params):
                if (isinstance(a, tuple) and a[0] == "binop"
                        and a[1] in ("plus", "minus")
                        and a[2] == ("id", p) and a[3] == ("num", 1)):
                    steppedp.add(p)
        bound_idx = None
        if cond[0] == "binop" and cond[1] in ("gt", "lt", "ge", "le"):
            for side in (cond[2], cond[3]):
                if (side[0] == "id" and side[1] in d.params
                        and side[1] not in steppedp):
                    bound_idx = d.params.index(side[1])
        if bound_idx is None or bound_idx >= len(args):
            raise LowerError(
                f"RECURSIVE {name}: cannot identify the stop bound")
        if not self._bounded_int_ast(args[bound_idx]):
            raise LowerError(
                f"RECURSIVE {name}: stop bound "
                f"{args[bound_idx]!r} is not layout-bounded; the "
                f"MAX_OPS unroll would truncate silently")

    # -- state-variable application ------------------------------------
    def _e_apply(self, e, env, st):
        _, fe, idx = e
        f = self.expr(fe, env, st)
        if f.kind == "statevar":
            i = self._rep_index(self.expr(idx, env, st))
            if f.kind2 == "rep":
                return d_int(st[f.plane][i], space=f.space)
            if f.kind2 == "replog":
                return d_log(st[f.plane][i], st[f.space][i])
            if f.kind2 == "repfn":
                return DV("vecrow", arr=st[f.plane][i])
            if f.kind2 == "tracker":
                return DV("trackrow", i=i, schema=f.plane)
        if f.kind == "vecrow":
            j = self._rep_index(self.expr(idx, env, st))
            return d_int(f.arr[j])
        if f.kind == "log":
            i = self.as_int(self.expr(idx, env, st))
            pos = jnp.clip(self._j(i) - self._j(f.first), 0,
                           self.MAX_OPS - 1)
            return DV("entry", v=jnp.asarray(f.arr, I32)[..., pos])
        if f.kind == "bag":
            mref = self.expr(idx, env, st)
            if mref.kind != "msg":
                raise LowerError("messages[x] needs a bag-bound x")
            return d_int(st["m_count"][mref.k])
        if f.kind == "auxfn":
            vid = self.as_int(self.expr(idx, env, st), space="value")
            cell = st["aux_acked"][jnp.clip(self._j(vid) - 1, 0,
                                            self.V - 1)]
            return d_bool(cell == 2)
        raise LowerError(f"cannot apply {f}")

    def _e_dot(self, e, env, st):
        _, be, fld = e
        b = self.expr(be, env, st)
        if b.kind == "msg":
            return self._msg_field(b, fld, st)
        if b.kind == "record":
            return b.fields[fld]
        if b.kind == "entry":
            return self.unpack_entry(b.v, fld)
        if b.kind == "tdvc":
            return self._tracker_field(b, fld, st)
        raise LowerError(f"cannot read field {fld} of {b}")

    def _tracker_field(self, ref, fld, st):
        i, j = ref.i, ref.j
        sc = self._schema(ref.schema)
        if fld == "source":
            return d_int(self._j(j) + 1, space="replica")
        if fld == "dest":
            return d_int(self._j(i) + 1, space="replica")
        if fld == "type":
            return d_static(self.consts[sc["type_const"]])
        if fld in sc["implied"]:
            return d_int(st[sc["implied"][fld]][i])
        p = sc["planes"].get(fld)
        if p is not None and fld != "log":
            return d_int(st[p][i][j])
        if fld == "view_number":
            vp = sc["view_plane"]
            if vp is None or vp not in self.planes:
                # implied = View(dest) (AS04-style layouts)
                return d_int(st["view"][i])
            return d_int(st[vp][i][j])
        if fld == sc.get("log_name", "log"):
            arrp, lenp = sc["log"]
            base = sc.get("suffix_base")
            vec = getattr(j, "ndim", 0) != 0 and not isinstance(j, int)
            if base is None:
                length = st[lenp][i][j]
                first = 1
            else:
                # suffix log: stored 0-based from prefix_ceil+1, length
                # op_number - prefix_ceil (Nil rows: op=-1, ceil=0 ->
                # length -1, the Nil sentinel)
                length = st[lenp][i][j] - st[base][i][j]
                first = st[base][i][j] + 1
            if vec:
                # vectorized element (inner quantifier): only the
                # Nil-test (length sentinel) is meaningful — arr=None
                # makes any other use fail loud in _as_log
                return d_log(None, length, first=first)
            return d_log(st[arrp][i, j], length, first=first)
        raise LowerError(f"tracker element has no field {fld}")

    def _msg_field(self, mref, fld, st):
        k = mref.k
        if fld == "log":
            if getattr(k, "ndim", 0) != 0 and not isinstance(k, int):
                raise LowerError("msg.log needs a scalar message ref")
            # uniform across kinds: DVC/SV carry no first_op (H_FIRST
            # stays 0 -> first=1, length=op_number); NewState stores
            # first_op and its m_log row 0-based from it (st03.py)
            first = jnp.maximum(st["m_hdr"][k, H_FIRST], 1)
            length = st["m_hdr"][k, H_OP] - first + 1
            return d_log(st["m_log"][k], length, first=first)
        if fld == "message":
            return DV("entry", v=st["m_entry"][k])
        if fld == "log_suffix":
            # AL05 recovery responses: suffix stored 0-based from
            # prefix_ceil+1 (H_FIRST holds the ceil; models/al05.py);
            # Nil rows have H_OP=-1 -> length sentinel -1
            first = st["m_hdr"][k, H_FIRST] + 1
            length = st["m_hdr"][k, H_OP] - st["m_hdr"][k, H_FIRST]
            return d_log(st["m_log"][k], length, first=first)
        col, space = MSG_FIELD_COLS[fld]
        return d_int(st["m_hdr"][..., col][k] if getattr(k, "ndim", 0)
                     else st["m_hdr"][k, col], space=space)

    # -- structures -----------------------------------------------------
    def _e_record(self, e, env, st):
        return DV("record", fields={n: self.expr(v, env, st)
                                    for n, v in e[1]})

    def _e_tuple(self, e, env, st):
        if not e[1]:
            return d_log(jnp.zeros((self.MAX_OPS,), I32), 0)
        raise LowerError("non-empty tuple literals unsupported")

    def _e_fnctor(self, e, env, st):
        _, groups, body = e
        if len(groups) != 1 or len(groups[0][0]) != 1:
            raise LowerError("multi-group function constructor")
        (names, dom) = groups[0]
        ddv = self.expr(dom, env, st)
        if ddv.kind == "intrange":
            # integer-domain constructor = a LOG value (the corpus's
            # log-slice idiom, e.g. ReceiveGetState's
            # [on \in m.op_number+1..rep_op_number[r] |-> ...],
            # ST03:472-474): vectorize the body over positions, store
            # 0-based from the (possibly traced) lower bound
            lo = self._j(self.as_int(ddv.lo))
            hi = self._j(self.as_int(ddv.hi))
            pos = jnp.arange(self.MAX_OPS, dtype=I32)
            on = d_int(lo + pos)
            val = self.expr(body, env.bind(names[0], on), st)
            codes = self._j(self.as_int(val))
            n = hi - lo + 1
            arr = jnp.where(pos < n, codes, 0)
            return d_log(arr, jnp.maximum(n, 0), first=lo)
        delems = self._set_elements(ddv)
        if delems is None:
            raise LowerError("function constructor over dynamic domain")
        vals = []
        for el, msk in delems:
            if msk is not None:
                raise LowerError("masked fnctor domain")
            v = self.expr(body, env.bind(names[0], el), st)
            vals.append(self._j(self.as_int(v)))
        return DV("vec", arr=jnp.stack([jnp.asarray(v, I32)
                                        for v in vals]))

    def _e_powerset(self, e, env, st):
        """SUBSET S for a static S (the corpus uses it only over
        `replicas`, A01:649/747) -> static set of frozensets."""
        from itertools import combinations
        from ..core.values import value_key
        s = self.expr(e[1], env, st)
        if s.kind == "static" and isinstance(s.v, frozenset):
            elems = sorted(s.v, key=value_key)
            subs = [frozenset(c) for r in range(len(elems) + 1)
                    for c in combinations(elems, r)]
            return d_static(frozenset(subs))
        raise LowerError("SUBSET of a dynamic set")

    def _e_setenum(self, e, env, st):
        return DV("dvset", elems=[self.expr(x, env, st) for x in e[1]])

    def _e_setfilter(self, e, env, st):
        _, var, sexpr, pred = e
        sdv = self.expr(sexpr, env, st)
        if sdv.kind != "trackrow":
            raise LowerError("set filter over unsupported domain")
        pres = self._schema(sdv.schema)["presence"]
        idx = jnp.arange(self.R, dtype=I32)
        mask = st[pres][sdv.i][idx] == 1
        ref = DV("tdvc", i=sdv.i, j=idx, schema=sdv.schema, axis=-1)
        b = self.expr(pred, env.deeper().bind(var, ref), st)
        return DV("trackset", i=sdv.i, schema=sdv.schema,
                  keep=mask & self._broad(b), adds=[])

    def _e_domain(self, e, env, st):
        b = self.expr(e[1], env, st)
        if b.kind == "bag":
            return DV("msgdom")
        if b.kind == "log":
            # log domains are layout-bounded by construction
            if isinstance(b.first, int):
                return DV("intrange", lo=d_static(b.first),
                          hi=d_int(self._j(b.length) + b.first - 1),
                          bounded=True)
            return DV("intrange", lo=d_int(b.first),
                      hi=d_int(self._j(b.length) + self._j(b.first) - 1),
                      bounded=True)
        if b.kind == "auxfn":
            elems = []
            for mv, vid in self.codec.value_id.items():
                elems.append((d_static(mv),
                              st["aux_acked"][vid - 1] > 0))
            return DV("maskedset", elems=elems)
        if b.kind == "statevar":
            return d_static(frozenset(range(1, self.R + 1)))
        raise LowerError(f"DOMAIN of {b}")

    # -- operators ------------------------------------------------------
    def _e_not(self, e, env, st):
        v = self.expr(e[1], env, st)
        if v.kind == "static":
            return d_static(not v.v)
        return d_bool(~self._jb(self.as_bool(v)))

    def _e_neg(self, e, env, st):
        v = self.expr(e[1], env, st)
        if v.kind == "static":
            return d_static(-v.v)
        return d_int(-self._j(self.as_int(v)))

    def _e_and(self, e, env, st):
        out = True
        for x in e[1]:
            v = self.expr(x, env, st)
            if v.kind == "static":
                if v.v is False:
                    return d_static(False)
                continue
            b = self.as_bool(v)
            out = b if out is True else (self._jb(out) & self._jb(b))
        return d_static(True) if out is True else d_bool(out)

    def _e_or(self, e, env, st):
        out = False
        for x in e[1]:
            v = self.expr(x, env, st)
            if v.kind == "static":
                if v.v is True:
                    return d_static(True)
                continue
            b = self.as_bool(v)
            out = b if out is False else (self._jb(out) | self._jb(b))
        return d_static(False) if out is False else d_bool(out)

    def _e_if(self, e, env, st):
        _, ce, te, ee = e
        if self._rec_cut:
            # recursion-cutoff level: the arm containing the recursive
            # call is unreachable (the unroll bound exceeds the data
            # bound) — compile only the base arm
            t_rec = any(self._refs_name(te, n) for n in self._rec_cut)
            e_rec = any(self._refs_name(ee, n) for n in self._rec_cut)
            if t_rec != e_rec:
                return self.expr(ee if t_rec else te, env, st)
        c = self.expr(ce, env, st)
        if c.kind == "static":
            return self.expr(te if c.v else ee, env, st)
        cb = self._jb(self.as_bool(c))
        tv = self.expr(te, env, st)
        ev = self.expr(ee, env, st)
        return self._select(cb, tv, ev)

    @staticmethod
    def _refs_name(e, name):
        if not isinstance(e, tuple):
            return False
        if e[0] in ("call", "id") and len(e) > 1 and e[1] == name:
            return True
        for x in e:
            if isinstance(x, tuple) and Lowerer._refs_name(x, name):
                return True
            if isinstance(x, list):
                for y in x:
                    if isinstance(y, tuple) and \
                            Lowerer._refs_name(y, name):
                        return True
        return False

    def _e_case(self, e, env, st):
        _, arms, other = e
        out = None if other is None else self.expr(other, env, st)
        for ge, ve in reversed(arms):
            g = self.expr(ge, env, st)
            v = self.expr(ve, env, st)
            if g.kind == "static":
                out = v if g.v else out
            else:
                if out is None:
                    out = v
                else:
                    out = self._select(self._jb(self.as_bool(g)), v, out)
        return out

    def _select(self, cb, a, b):
        # IF-arms mixing a value with Nil (RR05's recovery responses:
        # `log |-> IF primary THEN rep_log[r] ELSE Nil`) lower Nil to
        # the layout sentinel of the OTHER arm's kind: length -1 for
        # logs, -1 for ints (models/rr05.py)
        nil = self.consts.get("Nil")
        for x, y in ((a, b), (b, a)):
            if x.kind == "static" and x.v is nil and nil is not None:
                if y.kind == "log":
                    conv = d_log(jnp.zeros((self.MAX_OPS,), I32), -1)
                elif y.kind in ("int", "entry"):
                    conv = d_int(-1)
                else:
                    raise LowerError("IF-arm Nil of unsupported kind")
                if x is a:
                    a = conv
                else:
                    b = conv
        if a.kind == "log" or b.kind == "log":
            a, b = self._as_log(a), self._as_log(b)
            return d_log(jnp.where(cb, a.arr, b.arr),
                         jnp.where(cb, self._j(a.length),
                                   self._j(b.length)),
                         first=jnp.where(cb, self._j(a.first),
                                         self._j(b.first)))
        if a.kind == "bool" or b.kind == "bool":
            return d_bool(jnp.where(cb, self._jb(self.as_bool(a)),
                                    self._jb(self.as_bool(b))))
        sp = getattr(a, "space", None) or getattr(b, "space", None)
        return d_int(jnp.where(cb, self._j(self.as_int(a, sp)),
                               self._j(self.as_int(b, sp))), space=sp)

    def _e_let(self, e, env, st):
        _, defs, body = e
        env = self._bind_let(defs, env, st)
        self._ast_args.append({d.name: d.body for d in defs
                               if not d.params})
        try:
            return self.expr(body, env, st)
        finally:
            self._ast_args.pop()

    def _bind_let(self, defs, env, st):
        for d in defs:
            if d.params:
                env = env.bind(d.name, DV("opdef", d=d, env=env))
            else:
                env = env.bind(d.name, self.expr(d.body, env, st))
        return env

    def _e_lambda(self, e, env, st):
        return DV("opdef", d=Def(name="<lambda>", params=e[1], body=e[2]),
                  env=env)

    # -- binops ---------------------------------------------------------
    def _e_binop(self, e, env, st):
        _, op, le, re_ = e
        if op in ("in", "notin"):
            r = self.expr(re_, env, st)
            v = self._membership(le, r, env, st)
            if op == "notin":
                return d_static(not v.v) if v.kind == "static" \
                    else d_bool(~self._jb(v.v))
            return v
        a = self.expr(le, env, st)
        b = self.expr(re_, env, st)
        if op == "eq":
            return self._eq(a, b)
        if op == "ne":
            v = self._eq(a, b)
            return d_static(not v.v) if v.kind == "static" \
                else d_bool(~self._jb(v.v))
        if op == "range":
            return DV("intrange", lo=a, hi=b,
                      bounded=(b.kind == "static"
                               or self._bounded_int_ast(re_)))
        if op in ("lt", "gt", "le", "ge", "plus", "minus", "mod",
                  "div", "times"):
            sp = getattr(a, "space", None) or getattr(b, "space", None)
            if a.kind == "static" and b.kind == "static":
                x, y = a.v, b.v
                return d_static({
                    "lt": x < y, "gt": x > y, "le": x <= y,
                    "ge": x >= y, "plus": x + y, "minus": x - y,
                    "mod": x % y, "div": x // y, "times": x * y}[op])
            x = self._j(self.as_int(a, sp))
            y = self._j(self.as_int(b, sp))
            if op in ("lt", "gt", "le", "ge"):
                return d_bool({"lt": x < y, "gt": x > y,
                               "le": x <= y, "ge": x >= y}[op])
            return d_int({"plus": x + y, "minus": x - y, "mod": x % y,
                          "div": x // y, "times": x * y}[op],
                         space=sp)
        if op == "union":
            if a.kind == "trackrow":       # `@ \union {m}` (AS04:685)
                a = DV("trackset", i=a.i, schema=a.schema,
                       keep=st[self._schema(a.schema)["presence"]]
                       [a.i] == 1, adds=[])
            if b.kind == "trackrow":
                b = DV("trackset", i=b.i, schema=b.schema,
                       keep=st[self._schema(b.schema)["presence"]]
                       [b.i] == 1, adds=[])
            if a.kind == "trackset" and b.kind == "dvset":
                return DV("trackset", i=a.i, schema=a.schema,
                          keep=a.keep, adds=a.adds + b.elems)
            if a.kind == "dvset" and b.kind == "trackset":
                return DV("trackset", i=b.i, schema=b.schema,
                          keep=b.keep, adds=b.adds + a.elems)
            if a.kind == "static" and b.kind == "static":
                return d_static(a.v | b.v)
            raise LowerError("union of unsupported set kinds")
        if op == "merge":
            return DV("mergev", left=a, right=b, le=le, re=re_)
        if op == "mapsto":
            return DV("pointfn", key=a, val=b)
        if op == "setdiff":
            if a.kind == "static" and b.kind == "static":
                return d_static(a.v - b.v)
            if a.kind == "static" and b.kind == "maskedset":
                raise LowerError("setdiff with dynamic rhs")
            raise LowerError("setdiff unsupported here")
        raise LowerError(f"binop {op} unsupported")

    def _membership(self, le, rset, env, st):
        if rset.kind == "msgdom":
            el = self.expr(le, env, st)
            if el.kind == "msg":
                return d_bool(st["m_present"][el.k] == 1)
            if el.kind == "record":
                row = self.record_to_row(el, env, st)
                return d_bool(self.kern._row_eq(st, row).any())
            raise LowerError("x \\in DOMAIN messages for non-message x")
        el = self.expr(le, env, st)
        if rset.kind == "intrange":
            x = self._j(self.as_int(el))
            lo = self.as_int(rset.lo)
            hi = self.as_int(rset.hi)
            return d_bool((x >= self._j(lo)) & (x <= self._j(hi)))
        if rset.kind == "repmask":
            i = self._rep_index(el)
            return d_bool(rset.bits[i] == 1)
        if rset.kind == "maskedset":
            out = False
            for sel, msk in rset.elems:
                hit = self._eq(el, sel)
                hitb = hit.v if hit.kind != "static" else hit.v
                term = self._jb(hitb) & self._jb(msk) \
                    if hit.kind != "static" else \
                    (self._jb(msk) if hit.v else False)
                if term is False:
                    continue
                out = term if out is False \
                    else (self._jb(out) | self._jb(term))
            return d_static(False) if out is False else d_bool(out)
        if rset.kind == "static" and isinstance(rset.v, frozenset):
            if el.kind == "static":
                return d_static(any(tla_eq(el.v, x) for x in rset.v))
            out = False
            for x in rset.v:
                hit = self._eq(el, d_static(x))
                if hit.kind == "static":
                    if hit.v:
                        return d_static(True)
                    continue
                out = hit.v if out is False \
                    else (self._jb(out) | self._jb(hit.v))
            return d_static(False) if out is False else d_bool(out)
        raise LowerError(f"membership in {rset}")

    def _eq(self, a, b):
        if a.kind == "static" and b.kind == "static":
            return d_static(tla_eq(a.v, b.v))
        if a.kind == "log" or b.kind == "log":
            if b.kind == "log" and a.kind != "log":
                a, b = b, a
            if b.kind == "static" and b.v == ():
                return d_bool(self._j(a.length) == 0)
            if b.kind == "static" and b.v is self.consts.get("Nil"):
                # Nil-able log fields use a negative length sentinel
                # (models/rr05.py: H_OP/rec_op = -1 when log is Nil)
                return d_bool(self._j(a.length) < 0)
            a = self._as_log(a)
            b = self._as_log(b)
            # both arrays are stored 0-based from their `first`, so
            # equal domains = equal (first, length) and positional
            # array equality
            return d_bool((jnp.asarray(a.arr, I32)
                           == jnp.asarray(b.arr, I32)).all()
                          & (self._j(a.length) == self._j(b.length))
                          & (self._j(a.first) == self._j(b.first)))
        # int plane (0/1-coded) vs static boolean: compare codes
        if b.kind == "int" and a.kind == "static" \
                and isinstance(a.v, bool):
            a, b = b, a
        if a.kind == "int" and b.kind == "static" \
                and isinstance(b.v, bool):
            return d_bool(self._j(a.v) == int(b.v))
        if a.kind == "bool" or b.kind == "bool" or (
                a.kind == "static" and isinstance(a.v, bool)) or (
                b.kind == "static" and isinstance(b.v, bool)):
            return d_bool(self._jb(self.as_bool(a) if a.kind != "static"
                                   else a.v)
                          == self._jb(self.as_bool(b)
                                      if b.kind != "static" else b.v))
        sp = getattr(a, "space", None) or getattr(b, "space", None)
        return d_bool(self._j(self.as_int(a, sp))
                      == self._j(self.as_int(b, sp)))

    # -- quantifiers ----------------------------------------------------
    def _e_exists(self, e, env, st):
        return self._quant(e[1], e[2], env, st, mode="exists")

    def _e_forall(self, e, env, st):
        return self._quant(e[1], e[2], env, st, mode="forall")

    def _quant(self, groups, body, env, st, mode):
        flat = [(n, dom) for names, dom in groups for n in names]
        return self._quant_rec(flat, body, env, st, mode)

    def _vec_domain(self, dv, st, depth):
        """Vectorizable quantifier domain -> (idx, mask, ref_dv) with
        the element axis at -(depth+1), or None.  Covers the message
        bag and I01's per-replica DVC tracker rows."""
        if dv.kind == "msgdom":
            idx = jnp.arange(self.M, dtype=I32).reshape(
                (self.M,) + (1,) * depth)
            mask = st["m_present"][idx] == 1
            return idx, mask, d_msg(idx, mask=mask, axis=-(depth + 1))
        if dv.kind == "trackrow":
            pres = self._schema(dv.schema)["presence"]
            idx = jnp.arange(self.R, dtype=I32).reshape(
                (self.R,) + (1,) * depth)
            mask = st[pres][dv.i][idx] == 1
            return idx, mask, DV("tdvc", i=dv.i, j=idx,
                                 schema=dv.schema, axis=-(depth + 1))
        return None

    def _quant_rec(self, flat, body, env, st, mode):
        if not flat:
            v = self.expr(body, env, st)
            if v.kind == "static":
                return v
            return d_bool(self._jb(self.as_bool(v)))
        (name, dom), rest = flat[0], flat[1:]
        dv = self.expr(dom, env, st)
        vd = self._vec_domain(dv, st, env.depth)
        if vd is not None:
            d = env.depth
            _idx, mask, ref = vd
            inner = self._quant_rec(rest, body, env.deeper()
                                    .bind(name, ref), st, mode)
            bi = self._broad(inner)
            if mode == "exists":
                return d_bool((mask & bi).any(axis=-(d + 1)))
            return d_bool((~mask | bi).all(axis=-(d + 1)))
        if dv.kind == "intrange" and not (
                dv.lo.kind == "static" and dv.hi.kind == "static"):
            if not getattr(dv, "bounded", False):
                raise LowerError(
                    "dynamic integer range is not layout-bounded; "
                    "vectorizing over MAX_OPS+1 positions would "
                    "truncate it silently")
            d = env.depth
            lo = self.as_int(dv.lo)
            if not isinstance(lo, int):
                raise LowerError("dynamic range lower bound")
            idx = jnp.arange(lo, lo + self.MAX_OPS + 1,
                             dtype=I32).reshape(
                (self.MAX_OPS + 1,) + (1,) * d)
            mask = idx <= self._j(self.as_int(dv.hi))
            inner = self._quant_rec(
                rest, body,
                env.deeper().bind(name, d_int(idx)), st, mode)
            bi = self._broad(inner)
            if mode == "exists":
                return d_bool((mask & bi).any(axis=-(d + 1)))
            return d_bool((~mask | bi).all(axis=-(d + 1)))
        elems = self._set_elements(dv)
        if elems is None:
            raise LowerError(f"cannot enumerate domain {dv}")
        out = None
        for el, msk in elems:
            inner = self._quant_rec(rest, body, env.bind(name, el), st,
                                    mode)
            b = inner.v if inner.kind != "static" else inner.v
            if msk is not None:
                b = (self._jb(msk) & self._jb(b)) if mode == "exists" \
                    else (~self._jb(msk) | self._jb(b))
            if isinstance(b, bool):
                if mode == "exists" and b:
                    return d_static(True)
                if mode == "forall" and not b:
                    return d_static(False)
                continue
            out = b if out is None else (
                (self._jb(out) | self._jb(b)) if mode == "exists"
                else (self._jb(out) & self._jb(b)))
        if out is None:
            return d_static(mode == "forall")
        return d_bool(out)

    def _broad(self, dv):
        return self._jb(self.as_bool(dv)) if dv.kind != "static" \
            else jnp.asarray(dv.v)

    def _quantify(self, set_e, lam_e, env, st):
        """Quantify(S, LAMBDA x : P) -> count (FiniteSetsExt)."""
        lam = self.expr(lam_e, env, st)
        if lam.kind != "opdef":
            raise LowerError("Quantify needs a LAMBDA")
        pname = lam.d.params[0]
        sdv = self.expr(set_e, env, st)
        vd = self._vec_domain(sdv, st, env.depth)
        if vd is not None:
            d = env.depth
            _idx, mask, ref = vd
            body = self.expr(lam.d.body,
                             lam.env.deeper().bind(pname, ref), st)
            bi = self._broad(body)
            return d_int((mask & bi).sum(axis=-(d + 1), dtype=I32))
        elems = self._set_elements(sdv)
        if elems is None:
            raise LowerError("Quantify over non-enumerable set")
        n = jnp.asarray(0, I32)
        for el, msk in elems:
            b = self.expr(lam.d.body, lam.env.bind(pname, el), st)
            bi = self._jb(self.as_bool(b)) if b.kind != "static" \
                else b.v
            if msk is not None:
                bi = self._jb(bi) & self._jb(msk)
            n = n + jnp.asarray(bi, I32)
        return d_int(n)

    def _e_choose(self, e, env, st):
        _, var, sexpr, body = e
        sdv = self.expr(sexpr, env, st)
        if env.depth != 0:
            raise LowerError("nested CHOOSE")
        if sdv.kind == "trackrow":
            return self._choose_tracker(sdv, var, body, env, st)
        if sdv.kind != "msgdom":
            raise LowerError(
                "CHOOSE supported over DOMAIN messages / DVC trackers")
        d = env.depth
        idx = jnp.arange(self.M, dtype=I32).reshape((self.M,) + (1,) * d)
        mask = st["m_present"][idx] == 1
        mref = d_msg(idx, mask=mask, axis=-(d + 1))
        b = self.expr(body, env.deeper().bind(var, mref), st)
        cand = mask & self._broad(b)
        # deterministic tie-break: min value_key over the record columns
        # in alphabetical field order (core/values.py FnVal ordering)
        mtype = self._choose_msg_type(body)
        cols = []
        for fld in MSG_TYPE_FIELDS[mtype]:
            if fld == "log":
                cols.append(st["m_log"])
            elif fld == "message":
                cols.append(st["m_entry"][:, None])
            else:
                col, _sp = MSG_FIELD_COLS[fld]
                cols.append(st["m_hdr"][:, col][:, None])
        keys = jnp.concatenate([jnp.asarray(c, I32) for c in cols],
                               axis=1)
        for c in range(keys.shape[1]):
            col = jnp.where(cand, keys[:, c], INF)
            cand = cand & (col == col.min())
        return d_msg(jnp.argmax(cand).astype(I32))

    def _choose_tracker(self, trow, var, body, env, st):
        """Deterministic CHOOSE over a tracker row: min value_key among
        candidates, over the record columns in alphabetical field order
        (dest/type/implied fields are candidate-invariant and skipped;
        so is an implied view column)."""
        sc = self._schema(trow.schema)
        i = trow.i
        idx = jnp.arange(self.R, dtype=I32)
        mask = st[sc["presence"]][i][idx] == 1
        ref = DV("tdvc", i=i, j=idx, schema=trow.schema, axis=-1)
        b = self.expr(body, env.deeper().bind(var, ref), st)
        cand = mask & self._broad(b)
        cols = []
        for fld in sc["choose_cols"]:
            if fld == "log":
                cols.append(st[sc["log"][0]][i])
            elif fld == "source":
                cols.append((idx + 1)[:, None])
            elif fld == "view_number" and (
                    sc["view_plane"] is None
                    or sc["view_plane"] not in self.planes):
                continue
            elif fld == "view_number":
                cols.append(st[sc["view_plane"]][i][:, None])
            else:
                cols.append(st[sc["planes"][fld]][i][:, None])
        keys = jnp.concatenate([jnp.asarray(c, I32) for c in cols],
                               axis=1)
        for c in range(keys.shape[1]):
            col = jnp.where(cand, keys[:, c], INF)
            cand = cand & (col == col.min())
        return DV("tdvc", i=i, j=jnp.argmax(cand).astype(I32),
                  schema=trow.schema)

    def _choose_msg_type(self, body):
        """Find the `x.type = SomeMsg` constraint that fixes the CHOOSE
        candidates' record shape (all corpus CHOOSEs have one, possibly
        through an inlined operator like ValidDvc)."""
        found = []

        def walk(e, depth=0):
            if depth > 6 or not isinstance(e, tuple):
                return
            if e[0] == "binop" and e[1] == "eq":
                for a, b in ((e[2], e[3]), (e[3], e[2])):
                    if (isinstance(a, tuple) and a[0] == "dot"
                            and a[2] == "type"
                            and isinstance(b, tuple) and b[0] == "id"):
                        found.append(b[1])
            if e[0] in ("call", "id"):
                dd = self.module.defs.get(e[1])
                if dd is not None:
                    walk(dd.body, depth + 1)
            for x in e:
                if isinstance(x, tuple):
                    walk(x, depth)
                elif isinstance(x, list):
                    for y in x:
                        if isinstance(y, tuple):
                            walk(y, depth)
        walk(body)
        for name in found:
            mv = self.consts.get(name)
            if mv is not None:
                for const_name in MSG_TYPE_FIELDS:
                    if self.consts.get(const_name) is mv:
                        return const_name
                if name in MSG_TYPE_FIELDS:
                    return name
        raise LowerError(
            "CHOOSE over messages without a resolvable type constraint"
            + (f" (found {found})" if found else ""))

    # -- helpers --------------------------------------------------------
    def _set_elements(self, dv):
        """Static enumeration of a set DV: [(elem_dv, mask_or_None)]."""
        if dv.kind == "static" and isinstance(dv.v, frozenset):
            from ..core.values import value_key
            return [(d_static(x), None)
                    for x in sorted(dv.v, key=value_key)]
        if dv.kind == "maskedset":
            return list(dv.elems)
        if dv.kind == "intrange":
            if dv.lo.kind == "static" and dv.hi.kind == "static":
                return [(d_static(i), None)
                        for i in range(dv.lo.v, dv.hi.v + 1)]
            if not getattr(dv, "bounded", False):
                raise LowerError(
                    "dynamic integer range is not layout-bounded; "
                    "enumerating MAX_OPS+1 positions would truncate "
                    "it silently")
            lo = self.as_int(dv.lo)
            if isinstance(lo, int):
                hi = self._j(self.as_int(dv.hi))
                return [(d_static(i), hi >= i)
                        for i in range(lo, lo + self.MAX_OPS + 1)]
            return None
        if dv.kind == "repmask":
            return [(d_static(r), dv.bits[r - 1] == 1)
                    for r in range(1, self.R + 1)]
        return None

    def _rep_index(self, dv):
        """Replica-valued DV -> clipped 0-based row index."""
        r = self.as_int(dv, space="replica")
        if isinstance(r, int):
            return r - 1
        return jnp.clip(self._j(r) - 1, 0, self.R - 1)

    def _as_log(self, dv):
        if dv.kind == "log":
            if dv.arr is None:
                raise LowerError(
                    "log content of a vectorized tracker element "
                    "(only Nil-tests are supported there)")
            return dv
        if dv.kind == "static" and dv.v == ():
            return d_log(jnp.zeros((self.MAX_OPS,), I32), 0)
        raise LowerError(f"not a log: {dv}")

    def _loglen(self, dv):
        return self._as_log(dv).length

    def _entry_code(self, dv, env, st):
        if dv.kind == "record":
            return self._j(self.pack_entry(dv, env, st))
        if dv.kind == "entry":
            return self._j(dv.v)
        raise LowerError(f"not a log entry: {dv}")

    @staticmethod
    def _jb(x):
        return jnp.asarray(x, bool) if not isinstance(x, bool) else x

    def _schema(self, key):
        """Tracker schema for this module (module-specific layouts
        override the shared one: AL05's suffix responses)."""
        return TRACKER_SCHEMAS_BY_MODULE.get(
            (self.module.name, key)) or TRACKER_SCHEMAS[key]

    # ==================================================================
    # action compilation: binders -> lanes, conjuncts -> guards/updates
    # ==================================================================
    def _dims(self, air):
        sizes = {D_REPLICAS: self.R, D_VALUES: self.V, D_MSGS: self.M,
                 D_SUBSETS: 1 << self.R, D_TRACKER: self.R,
                 D_INTRANGE: self.MAX_OPS + 1}
        return [sizes[b.domain] for b in air.binders]

    def lane_count(self, air):
        n = 1
        for d in self._dims(air):
            n *= d
        return n

    def _bind_lanes(self, air, st, lane, guards):
        """Mixed-radix lane decode (first binder most significant)."""
        dims = self._dims(air)
        env = Env()
        rem = jnp.asarray(lane, I32)
        for bi, b in enumerate(air.binders):
            rest = 1
            for d in dims[bi + 1:]:
                rest *= d
            comp = rem // rest
            rem = rem % rest
            if b.domain == D_REPLICAS:
                env = env.bind(b.name, d_int(comp + 1, space="replica"))
            elif b.domain == D_VALUES:
                env = env.bind(b.name, d_int(comp + 1, space="value"))
            elif b.domain == D_MSGS:
                env = env.bind(b.name, d_msg(comp))
                guards.append(st["m_present"][comp] == 1)
            elif b.domain == D_SUBSETS:
                bits = (comp >> jnp.arange(self.R, dtype=I32)) & 1
                env = env.bind(b.name, DV("repmask", bits=bits))
            elif b.domain == D_TRACKER:
                tvar, owner = b.info
                odv = env.vars[owner]
                i = self._rep_index(odv)
                pres = self._schema(tvar)["presence"]
                env = env.bind(b.name, DV("tdvc", i=i, j=comp,
                                          schema=tvar))
                guards.append(st[pres][i][comp] == 1)
            elif b.domain == D_INTRANGE:
                lo, hi_ast = b.info
                if not self._bounded_int_ast(hi_ast):
                    raise LowerError(
                        f"range binder bound {hi_ast!r} is not "
                        f"layout-bounded; {self.MAX_OPS + 1} lanes "
                        f"would truncate it silently")
                val = lo + comp
                env = env.bind(b.name, d_int(val))
                hi = self._j(self.as_int(self.expr(hi_ast, env, st)))
                guards.append(val <= hi)
        return env

    def compile_action(self, air):
        def act(st, lane):
            guards = []
            env = self._bind_lanes(air, st, lane, guards)
            s2 = self._walk(air.body, env, st, dict(st), guards,
                            build=True)
            return s2, self._and_all(guards)

        def guard(st, lane):
            guards = []
            env = self._bind_lanes(air, st, lane, guards)
            self._walk(air.body, env, st, None, guards, build=False)
            return self._and_all(guards)

        rep_idx_ast = self._rep_index_ast(air)

        def lane_rep(st, lane):
            if rep_idx_ast is None:
                return jnp.zeros((), I32)
            env = self._bind_lanes(air, st, lane, [])
            i = self._rep_index(self.expr(rep_idx_ast, env, st))
            return jnp.asarray(i, I32)

        return guard, act, lane_rep

    def _and_all(self, guards):
        out = jnp.asarray(True)
        for g in guards:
            if isinstance(g, bool):
                if not g:
                    return jnp.asarray(False)
                continue
            out = out & self._jb(g)
        return out

    # -- conjunct walker ------------------------------------------------
    def _walk(self, node, env, st, s2, guards, build):
        tag = node[0]
        if tag == "and":
            for x in node[1]:
                s2 = self._walk(x, env, st, s2, guards, build)
            return s2
        if tag == "let":
            env2 = self._bind_let(node[1], env, st)
            self._ast_args.append({d.name: d.body for d in node[1]
                                   if not d.params})
            try:
                return self._walk(node[2], env2, st, s2, guards, build)
            finally:
                self._ast_args.pop()
        if tag == "unchanged":
            return s2
        if (tag == "binop" and node[1] == "eq"
                and isinstance(node[2], tuple)
                and node[2][0] == "prime"):
            if node[2][1][0] != "id":
                raise LowerError("primed non-variable")
            if build:
                s2 = self._update(node[2][1][1], node[3], env, st, s2)
            return s2
        if tag in ("call", "id") and contains_prime(node, self.module):
            name = node[1]
            d = self.module.defs.get(name)
            if name in env.vars and env.vars[name].kind == "opdef":
                od = env.vars[name]
                d, callenv = od.d, od.env
            elif d is not None:
                callenv = env
            else:
                raise LowerError(f"unknown updater {name}")
            args = node[2] if tag == "call" else []
            vals = {p: self.expr(a, env, st)
                    for p, a in zip(d.params, args)}
            # syntactic args too: the bag walker needs the ASTs of
            # `messages`-typed parameters
            asts = dict(zip(d.params, args))
            return self._walk_inlined(d.body, callenv.bind_many(vals),
                                      asts, st, s2, guards, build)
        if tag == "or" and contains_prime(node, self.module):
            return self._walk_or(node[1], env, st, s2, guards, build)
        if tag == "if" and contains_prime(node, self.module):
            c = self._jb(self.as_bool(self.expr(node[1], env, st)))
            return self._walk_branches(
                [(c, node[2]), (None, node[3])], env, st, s2, guards,
                build)
        # plain guard conjunct
        v = self.expr(node, env, st)
        if v.kind == "static":
            if v.v is not True:
                guards.append(False)
        else:
            guards.append(self.as_bool(v))
        return s2

    def _walk_inlined(self, body, env, arg_asts, st, s2, guards, build):
        """Walk an inlined operator body.  `arg_asts` keeps the callers'
        argument ASTs so `messages'`-update RHS combinator matching can
        resolve parameters syntactically."""
        self._ast_args.append(arg_asts)
        try:
            return self._walk(body, env, st, s2, guards, build)
        finally:
            self._ast_args.pop()

    def _walk_or(self, branches, env, st, s2, guards, build):
        conds, subs = [], []
        for br in branches:
            g = []
            sb = self._walk(br, env, st,
                            dict(s2) if build else None, g, build)
            conds.append(self._and_all(g))
            subs.append(sb)
        en = jnp.asarray(False)
        for c in conds:
            en = en | c
        guards.append(en)
        if not build:
            return s2
        # guard-exclusive branches (corpus invariant): select by guard
        acc = subs[-1]
        for c, sb in zip(conds[-2::-1], subs[-2::-1]):
            acc = {k: jnp.where(c, sb[k], acc[k]) for k in acc}
        return acc

    def _walk_branches(self, cond_branches, env, st, s2, guards, build):
        """IF/ELSE with updates: cond_branches = [(c, node), (None,
        else_node)]."""
        (c, tnode), (_, enode) = cond_branches
        gt, ge = [], []
        s2t = self._walk(tnode, env, st, dict(s2) if build else None,
                         gt, build)
        s2e = self._walk(enode, env, st, dict(s2) if build else None,
                         ge, build)
        guards.append(jnp.where(c, self._and_all(gt),
                                self._and_all(ge)))
        if not build:
            return s2
        return {k: jnp.where(c, s2t[k], s2e[k]) for k in s2t}

    # -- updates --------------------------------------------------------
    def _update(self, var, rhs, env, st, s2):
        vk = VAR_KINDS.get(var)
        if vk is None:
            raise LowerError(f"update to unmapped variable {var}")
        kind, plane, space = vk
        if kind == "bag":
            return self._apply_bag(rhs, env, st, s2)
        if rhs[0] == "except" and rhs[1] == ("id", var):
            for path, val_e in rhs[2]:
                s2 = self._apply_except(kind, plane, space, path, val_e,
                                        env, st, s2)
            return s2
        if kind == "glob":
            s2[plane] = self._j(self.as_int(self.expr(rhs, env, st),
                                            space))
            return s2
        if kind == "rep" and rhs[0] == "fnctor":
            if plane in getattr(self.kern, "REP_KEYS", ()):
                raise LowerError(
                    f"whole-plane update to hashed per-replica plane "
                    f"{plane} breaks incremental fingerprints")
            vec = self.expr(rhs, env, st)
            s2[plane] = vec.arr
            return s2
        if kind == "auxfn" and rhs[0] == "binop" and rhs[1] == "merge" \
                and rhs[2] == ("id", var) \
                and rhs[3][0] == "binop" and rhs[3][1] == "mapsto":
            vid = self._j(self.as_int(
                self.expr(rhs[3][2], env, st), "value"))
            enc = self._aux_bool_enc(self.expr(rhs[3][3], env, st))
            idx = jnp.clip(vid - 1, 0, self.V - 1)
            cur = st[plane][idx]
            # left-biased @@: only absent keys take the new value
            s2[plane] = st[plane].at[idx].set(
                jnp.where(cur == 0, enc, cur))
            return s2
        raise LowerError(f"unsupported update form for {var}: {rhs[0]}")

    def _apply_except(self, kind, plane, space, path, val_e, env, st,
                      s2):
        if path[0][0] != "idx":
            raise LowerError("EXCEPT field path on state variable")
        i = self._rep_index(self.expr(path[0][1], env, st)) \
            if kind in ("rep", "replog", "repfn", "tracker") else None
        if kind == "rep":
            cur = d_int(st[plane][i], space=space)
            val = self.expr(val_e, env.bind("@", cur), st)
            s2[plane] = st[plane].at[i].set(
                self._j(self.as_int(val, space)))
            return s2
        if kind == "replog":
            cur = d_log(st[plane][i], st[space][i])
            val = self._as_log(self.expr(val_e, env.bind("@", cur), st))
            s2[plane] = st[plane].at[i].set(
                jnp.asarray(val.arr, I32))
            return s2
        if kind == "repfn":
            if len(path) == 2:
                j = self._rep_index(self.expr(path[1][1], env, st))
                cur = d_int(st[plane][i, j])
                val = self.expr(val_e, env.bind("@", cur), st)
                s2[plane] = st[plane].at[i, j].set(
                    self._j(self.as_int(val)))
                return s2
            val = self.expr(val_e, env, st)
            if val.kind != "vec":
                raise LowerError("row update needs a function value")
            s2[plane] = st[plane].at[i].set(val.arr)
            return s2
        if kind == "auxfn":
            vid = self._j(self.as_int(self.expr(path[0][1], env, st),
                                      "value"))
            enc = self._aux_bool_enc(self.expr(val_e, env, st))
            s2[plane] = st[plane].at[
                jnp.clip(vid - 1, 0, self.V - 1)].set(enc)
            return s2
        if kind == "tracker":
            cur = DV("trackrow", i=i, schema=plane)
            val = self.expr(val_e, env.bind("@", cur), st)
            return self._tracker_assign(plane, i, val, st, s2)
        raise LowerError(f"EXCEPT on {kind}")

    @staticmethod
    def _aux_bool_enc(bval):
        """aux_client_acked cell encoding (absent=0/FALSE=1/TRUE=2).
        Only literal booleans appear in the corpus; anything traced
        must raise (fail-loud contract), not silently encode FALSE."""
        if bval.kind == "static" and isinstance(bval.v, bool):
            return 2 if bval.v else 1
        raise LowerError(
            "aux_client_acked updates support literal TRUE/FALSE only")

    def _tracker_planes(self, schema):
        sc = self._schema(schema)
        planes = [sc["presence"]]
        if sc["view_plane"] and sc["view_plane"] in self.planes:
            planes.append(sc["view_plane"])
        planes.extend(sorted(set(sc["planes"].values())))
        planes.append(sc["log"][0])
        if sc["has_flag"]:
            planes.append(sc["has_flag"])
        return [p for p in dict.fromkeys(planes) if p in self.planes]

    def _tracker_assign(self, schema, i, val, st, s2):
        """tracker[r] := {} / {elements} / filtered-set U {elements}.
        Dropped slots are ZEROED in every plane (non-present slots must
        be all-zero or the per-replica row hash loses canonicity)."""
        sc = self._schema(schema)
        if val.kind == "dvset":
            keep = jnp.zeros((self.R,), bool)
            adds = list(val.elems)
        elif val.kind == "trackset":
            if val.schema != schema:
                raise LowerError(
                    "tracker value from a different tracker")
            keep, adds = val.keep, val.adds
        else:
            raise LowerError(f"unsupported tracker value {val}")
        planes = self._tracker_planes(schema)
        rows = {}
        for p in planes:
            row = st[p][i]
            km = keep if row.ndim == 1 else keep[:, None]
            rows[p] = jnp.where(km, row, 0)
        for el in adds:
            f = self._tracker_insert_fields(sc, el, st)
            j = jnp.clip(f.pop("j"), 0, self.R - 1)
            rows[sc["presence"]] = rows[sc["presence"]].at[j].set(1)
            for p, v in f.items():
                rows[p] = rows[p].at[j].set(v)
        for p in planes:
            s2[p] = st[p].at[i].set(rows[p])
        return s2

    def _tracker_insert_fields(self, sc, el, st):
        """Element DV -> {plane: value} for one slot insert (plus the
        slot index under 'j')."""
        if el.kind == "msg":
            k = el.k
            hdr = st["m_hdr"][k]
            out = {"j": hdr[H_SRC] - 1,
                   sc["log"][0]: jnp.asarray(st["m_log"][k], I32)}
            for fld, p in sc["planes"].items():
                out[p] = hdr[MSG_FIELD_COLS[fld][0]]
            if sc["view_plane"] and sc["view_plane"] in self.planes:
                out[sc["view_plane"]] = hdr[H_VIEW]
            if sc["has_flag"]:
                out[sc["has_flag"]] = jnp.asarray(hdr[H_OP] >= 0, I32)
            return out
        if el.kind == "record":
            f = el.fields
            lg = self._as_log(f["log"])
            out = {"j": self._j(self.as_int(f["source"],
                                            "replica")) - 1,
                   sc["log"][0]: jnp.asarray(lg.arr, I32)}
            for fld, p in sc["planes"].items():
                out[p] = self._j(self.as_int(f[fld]))
            if sc["view_plane"] and sc["view_plane"] in self.planes:
                out[sc["view_plane"]] = self._j(
                    self.as_int(f["view_number"]))
            if sc["has_flag"]:
                out[sc["has_flag"]] = jnp.asarray(
                    self._j(lg.length) >= 0, I32)
            return out
        raise LowerError(f"cannot insert {el} into a tracker")

    # -- bag combinators ------------------------------------------------
    def _apply_bag(self, rhs, env, st, s2):
        """messages' = <combinator tree> -> base-kernel bag primitives.
        Recurses into the msgs argument first, so DiscardFunc composed
        under SendFunc/BroadcastFunc applies in evaluation order."""
        if rhs == ("id", "messages"):
            return s2
        if rhs[0] == "id":
            # a `messages`-typed parameter of an inlined wrapper: chase
            # the caller's argument AST
            for frame in reversed(self._ast_args):
                if rhs[1] in frame:
                    return self._apply_bag(frame[rhs[1]], env, st, s2)
            raise LowerError(f"opaque messages value {rhs[1]}")
        if rhs[0] != "call":
            raise LowerError(f"unsupported messages' RHS {rhs[0]}")
        name, args = rhs[1], rhs[2]
        if name == "SendFunc":
            m_e, msgs_e = args[0], args[1]
            cnt = self.expr(args[2], env, st) if len(args) > 2 \
                else d_static(1)
            s2 = self._apply_bag(msgs_e, env, st, s2)
            rec = self.expr(m_e, env, st)
            row = self.record_to_row(rec, env, st)
            return self.kern._bag_send(
                s2, row, new_count=self._j(self.as_int(cnt)))
        if name == "BroadcastFunc":
            msg_e, src_e, msgs_e = args[0], args[1], args[2]
            s2 = self._apply_bag(msgs_e, env, st, s2)
            rec = self.expr(msg_e, env, st)
            row = self.record_to_row(rec, env, st)
            src = self._j(self.as_int(self.expr(src_e, env, st),
                                      "replica"))
            return self.kern._broadcast(s2, row, src)
        if name == "DiscardFunc":
            d_e, msgs_e = args[0], args[1]
            s2 = self._apply_bag(msgs_e, env, st, s2)
            mref = self.expr(d_e, env, st)
            if mref.kind != "msg":
                raise LowerError("DiscardFunc of a non-reference")
            return self.kern._bag_discard(s2, mref.k)
        # wrapper operator (Send/Discard/... passed through a LET):
        d = self.module.defs.get(name)
        if d is not None:
            raise LowerError(
                f"messages' RHS calls {name}; expected the SendFunc/"
                f"BroadcastFunc/DiscardFunc combinators after inlining")
        raise LowerError(f"unknown bag combinator {name}")

    # -- static lane->replica analysis ----------------------------------
    def _rep_index_ast(self, air):
        """The one replica-index expression used by this action's
        per-replica-plane updates (None when it touches none) — powers
        kern.lane_replica for incremental fingerprinting."""
        found = []
        rep_planes = set(getattr(self.kern, "REP_KEYS", ()))

        def subst(e, binds):
            if not isinstance(e, tuple):
                return e
            if e[0] == "id" and e[1] in binds:
                return binds[e[1]]
            return tuple(
                subst(x, binds) if isinstance(x, tuple)
                else ([subst(y, binds) if isinstance(y, tuple) else y
                       for y in x] if isinstance(x, list) else x)
                for x in e)

        def walk(e, binds, depth=0):
            if depth > 8 or not isinstance(e, tuple):
                return
            if (e[0] == "binop" and e[1] == "eq"
                    and isinstance(e[2], tuple)
                    and e[2][0] == "prime" and e[2][1][0] == "id"):
                var = e[2][1][1]
                vk = VAR_KINDS.get(var)
                plane = None
                if vk and vk[0] == "tracker":
                    plane = self._schema(vk[1])["presence"]
                elif vk and vk[0] in ("rep", "replog", "repfn"):
                    plane = vk[1]
                if plane is not None and plane in rep_planes:
                    rhs = e[3]
                    if rhs[0] == "except":
                        path = rhs[2][0][0]
                        found.append(subst(path[0][1], binds))
                    else:
                        raise LowerError(
                            f"non-EXCEPT update to hashed plane {var}")
                return
            if e[0] in ("call", "id"):
                d = self.module.defs.get(e[1])
                if d is not None and contains_prime(d.body, self.module):
                    args = e[2] if e[0] == "call" else []
                    nb = dict(zip(d.params,
                                  [subst(a, binds) for a in args]))
                    walk(d.body, nb, depth + 1)
                    return
            for x in e:
                if isinstance(x, tuple):
                    walk(x, binds, depth)
                elif isinstance(x, list):
                    for y in x:
                        if isinstance(y, tuple):
                            walk(y, binds, depth)

        walk(air.body, {})
        if not found:
            return None
        first = found[0]
        for f in found[1:]:
            if f != first:
                raise LowerError(
                    f"action {air.name} updates replica planes at "
                    f"differing indices {first} vs {f}")
        return first

    # ==================================================================
    # record -> bag row
    # ==================================================================
    def record_to_row(self, rec, env, st):
        f = rec.fields
        kw = {}
        t = f["type"]
        kw["type_"] = self.enc_static(t.v, "mtype") \
            if t.kind == "static" else self.as_int(t, "mtype")
        nil = self.consts.get("Nil")
        ls = f.get("log_suffix")
        if ls is not None and ls.kind == "static" and ls.v is nil:
            # AL05 backup response form: log_suffix=Nil encodes as
            # op/commit -1 sentinels and a zero log row (al05.py)
            kw["op"] = -1
            kw["commit"] = -1
        for fld, dv in f.items():
            if fld == "type":
                continue
            if fld == "message":
                kw["entry"] = self._entry_code(dv, env, st)
            elif fld == "log":
                kw["log"] = jnp.asarray(self._as_log(dv).arr, I32)
            elif fld == "log_suffix":
                if not (dv.kind == "static" and dv.v is nil):
                    kw["log"] = jnp.asarray(self._as_log(dv).arr, I32)
            else:
                col_kw = {"view_number": "view", "op_number": "op",
                          "commit_number": "commit", "dest": "dest",
                          "source": "src", "last_normal_vn": "lnv",
                          "first_op": "first", "x": "x", "op": "op",
                          "prefix_ceil": "first"}[fld]
                kw[col_kw] = self._j(self.as_int(
                    dv, space=MSG_FIELD_COLS[fld][1]))
        return self.kern._row(**kw)

    # ==================================================================
    # invariants
    # ==================================================================
    def compile_pred(self, body):
        def pred(st):
            v = self.expr(body, Env(), st)
            if v.kind == "static":
                return jnp.asarray(bool(v.v))
            return self._jb(self.as_bool(v))
        return pred


# ======================================================================
# compiled kernel factory
# ======================================================================
def make_compiled_model(spec, max_msgs=None, fold_symmetry=True):
    """Build (codec, kernel) where every guard/action/invariant fn is
    COMPILED FROM THE SPEC AST (ir.extract_action -> Lowerer) instead of
    hand-written.  The dense layout, bag primitives, fingerprint and
    lane machinery are inherited from the spec family's base kernel
    class; the hand kernel remains available separately as the
    differential oracle (tests/test_lower.py)."""
    from ..models import registry

    # direct callers (tests/test_lower.py, scripts) bypass make_model,
    # so set up the persistent compilation cache here too — the jitted
    # level kernels built from these models take minutes to compile
    registry.ensure_compile_cache()
    codec_cls, base_cls = registry._resolve(spec.module.name)
    codec = codec_cls(spec.ev.constants, max_msgs=max_msgs)
    perms = registry.value_perm_table(spec, codec,
                                      fold_symmetry=fold_symmetry)

    class CompiledKernel(base_cls):
        compiled_from_ast = True

        def __init__(self, codec, spec, perms):
            self._spec = spec
            self._irs = [extract_action(a.name, a.expr)
                         for a in spec.actions]
            self.action_names = tuple(ir.name for ir in self._irs)
            # lane counts are needed by the base __init__ (lane
            # tables); they only depend on binder domains and shape
            pre = Lowerer(spec, codec, kern=None)
            self._lane_counts = {ir.name: pre.lane_count(ir)
                                 for ir in self._irs}
            super().__init__(codec, perms=perms)
            self.lowerer = Lowerer(spec, codec, kern=self)
            self._cguard, self._cact, self._clanerep = {}, {}, {}
            for ir in self._irs:
                g, a, lr = self.lowerer.compile_action(ir)
                self._cguard[ir.name] = g
                self._cact[ir.name] = a
                self._clanerep[ir.name] = lr
            # fail FAST on unsupported constructs: abstractly trace
            # every action now (cheap — no compilation), so a module
            # beyond the lowerer's surface raises LowerError at build
            # time instead of at first kernel dispatch
            zero = {k: jax.ShapeDtypeStruct(np.shape(v), jnp.int32)
                    for k, v in codec.zero_state().items()}
            lane = jax.ShapeDtypeStruct((), jnp.int32)
            for ir in self._irs:
                try:
                    jax.eval_shape(self._cact[ir.name], zero, lane)
                    jax.eval_shape(self._cguard[ir.name], zero, lane)
                except LowerError:
                    raise
                except Exception as e:  # noqa: BLE001
                    raise LowerError(
                        f"action {ir.name} failed abstract tracing: "
                        f"{type(e).__name__}: {e}") from e

        def _lane_count(self, name):
            return self._lane_counts[name]

        def _guard_fns(self):
            return [self._cguard[n] for n in self.action_names]

        def _action_fns(self):
            return [self._cact[n] for n in self.action_names]

        def lane_replica(self, name, st, lane):
            return self._clanerep[name](st, lane)

        def invariant_fn(self, names):
            preds = []
            for n in names:
                d = self._spec.module.defs.get(n)
                if d is None:
                    raise LowerError(f"invariant {n} not defined")
                preds.append(self.lowerer.compile_pred(d.body))

            def check(st):
                ok = jnp.asarray(True)
                for p in preds:
                    ok = ok & p(st)
                return ok
            return check

    return codec, CompiledKernel(codec, spec, perms)
