"""Guarded-command IR extraction (SURVEY.md §7.4 `ir/`).

A reference action is uniformly shaped (SURVEY.md §2.2):

    \\E r \\in replicas [, m \\in DOMAIN messages, v \\in Values, ...] :
        guard conjuncts /\\ primed updates /\\ UNCHANGED frame

This module turns a parsed action expression (frontend/parser.py AST)
into an ``ActionIR``: the ordered LANE BINDERS (the existentials the
device kernel enumerates as one lane per combination) plus the body
conjunct tree, with utilities the lowerer (lower/compile.py) uses to
classify conjuncts as guards vs. updates.

Only the *top-level* existential chain is lifted into lane binders —
quantifiers inside guards (Quantify lambdas, CHOOSE maximality checks)
stay expression-level and are vectorized by the lowerer instead.
"""

from __future__ import annotations

from dataclasses import dataclass, field

# lane-binder domain tags
D_REPLICAS = "replicas"
D_VALUES = "values"
D_MSGS = "msgs"
D_SUBSETS = "subsets"
D_TRACKER = "tracker"    # `\E m \in rep_rec_recv[r]` with updates
                         # inside (RR05's CompleteRecovery) — one lane
                         # per tracker slot
D_INTRANGE = "intrange"  # `\E last_op \in 0..rep_op_number[r]` with
                         # updates inside (AL05's prefix crash) — one
                         # lane per log position (span is layout-
                         # bounded by MAX_OPS)

# tracker state variables whose per-replica rows are lane-enumerable
TRACKER_VARS = ("rep_recv_dvc", "rep_rec_recv")


@dataclass
class Binder:
    name: str
    domain: str          # one of the D_* tags
    info: tuple = None   # D_TRACKER: (tracker var name, owner binder)


@dataclass
class ActionIR:
    name: str
    binders: list = field(default_factory=list)
    body: tuple = None   # conjunct tree (everything under the binders)


def classify_domain(dom_expr, bound_names=()):
    """Map a binder's domain expression to (tag, info), or None if it
    is not lane-enumerable (left as an inner quantifier)."""
    if dom_expr == ("id", "replicas"):
        return D_REPLICAS, None
    if dom_expr == ("id", "Values"):
        return D_VALUES, None
    if dom_expr[0] == "domain" and dom_expr[1] == ("id", "messages"):
        return D_MSGS, None
    if dom_expr[0] == "powerset" and dom_expr[1] == ("id", "replicas"):
        return D_SUBSETS, None
    if (dom_expr[0] == "apply" and dom_expr[1][0] == "id"
            and dom_expr[1][1] in TRACKER_VARS
            and dom_expr[2][0] == "id"
            and dom_expr[2][1] in bound_names):
        return D_TRACKER, (dom_expr[1][1], dom_expr[2][1])
    if (dom_expr[0] == "binop" and dom_expr[1] == "range"
            and dom_expr[2][0] == "num"):
        return D_INTRANGE, (dom_expr[2][1], dom_expr[3])
    return None


def extract_action(name, expr) -> ActionIR:
    """Lift the top-level existential chain of an action body into lane
    binders.  Handles both shapes in the corpus: binders outermost
    (ReceiveClientRequest) and binders behind leading guard conjuncts
    (TimerSendSVC's ``aux_svc < Limit /\\ \\E r : ...``,
    NoProgressChange's counter guard)."""
    binders = []
    rest = []

    def bound():
        return tuple(b.name for b in binders)

    def walk(e):
        if e[0] == "and":
            items = list(e[1])
            ex = [i for i, x in enumerate(items)
                  if x[0] == "exists" and _liftable(x, bound())]
            if len(ex) == 1:
                inner = items.pop(ex[0])
                rest.extend(items)
                walk(inner)
            else:
                rest.append(e)
        elif e[0] == "exists" and _liftable(e, bound()):
            for names, dom in e[1]:
                tag, info = classify_domain(dom, bound())
                for n in names:
                    binders.append(Binder(n, tag, info))
            walk(e[2])
        else:
            rest.append(e)

    walk(expr)
    body = rest[0] if len(rest) == 1 else ("and", rest)
    return ActionIR(name=name, binders=binders, body=body)


def _liftable(e, bound_names):
    if e[0] != "exists":
        return False
    return all(classify_domain(dom, bound_names) is not None
               for _names, dom in e[1])


def contains_prime(e, module, _seen=None) -> bool:
    """Does this expression (transitively through operator definitions
    in `module`) prime any state variable?  Used to classify conjuncts
    as updates (ResetSentVars, Send, DiscardAndBroadcast, ... all prime
    through their definitions)."""
    if _seen is None:
        _seen = set()
    if not isinstance(e, tuple):
        return False
    if e[0] == "prime":
        return True
    if e[0] in ("call", "id"):
        name = e[1]
        d = module.defs.get(name)
        if d is not None and name not in _seen:
            _seen.add(name)
            if contains_prime(d.body, module, _seen):
                return True
    for x in e:
        if isinstance(x, tuple) and contains_prime(x, module, _seen):
            return True
        if isinstance(x, list):
            for y in x:
                if isinstance(y, tuple) and contains_prime(y, module, _seen):
                    return True
    return False
