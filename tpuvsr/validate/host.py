"""Interpreter-level trace validation (ISSUE 8) — the reference
semantics and the batch validator's confirmer.

Per recorded event, the candidate set (every spec state consistent
with the observations so far) is advanced through
``spec.successors``: a successor survives iff its producing action
matches the recorded one (when observed) and its state agrees with
the recorded partial assignment on every observed variable.  An empty
next candidate set IS the divergence — the implementation took a step
the spec does not allow — and the report carries the spec-side
enabled action set at that point (the dual of ``frontend.trace_parse
.replay_trace``, which asks the opposite question of a
checker-produced trace).

This path is fully general (any value type the interpreter handles)
and jax-free; ``batch.py`` is the vmapped/sharded production engine
and calls back into this module to confirm each device-reported
divergence (the fleet's device/interpreter cross-check idiom).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from ..core.values import TLAError


@dataclass
class ValidateResult:
    """Result of validating a batch of traces.  ``divergences`` holds
    one record per diverged trace, in trace order — bit-identical
    across mesh sizes, batch sizes, and rescue/resume seams (the
    acceptance contract); ``first_divergence`` is the headline."""

    ok: bool = True
    traces_checked: int = 0
    accepted: int = 0
    divergences: list = field(default_factory=list)
    elapsed: float = 0.0
    metrics: dict = None
    batch: int = 0
    error: str = None

    @property
    def first_divergence(self):
        return self.divergences[0] if self.divergences else None

    @property
    def traces_per_sec(self):
        return self.traces_checked / self.elapsed if self.elapsed > 0 \
            else 0.0


@dataclass
class HostVerdict:
    """Per-trace verdict of the interpreter validator."""

    tid: str
    ok: bool
    diverged_at: int = None     # event index of the divergence
    enabled: list = None        # [(action_name, location), ...] there
    candidates: int = 0         # candidate-set size at the divergence
    max_candidates: int = 1     # peak candidate-set size seen
    violated_invariant: str = None   # first invariant every candidate
    violated_at: int = None          # broke, and the event index


def _obs_matches(st, obs):
    """State agreement on every observed variable (names were already
    checked against the spec at trace load)."""
    for k, v in obs.items():
        if st[k] != v:
            return False
    return True


def _state_key(st):
    from ..core.values import value_key
    return tuple((k, value_key(v)) for k, v in sorted(st.items()))


def validate_trace(spec, trace, max_candidates=4096) -> HostVerdict:
    """Validate ONE trace against the spec (module docstring).  Raises
    ``TLAError`` when the candidate set exceeds ``max_candidates``
    (an under-observed trace of a wide spec — not a divergence)."""
    v = HostVerdict(tid=trace.tid, ok=True)
    cands = [st for st in spec.init_states()
             if _obs_matches(st, trace.init)]
    if not cands:
        v.ok = False
        v.diverged_at = 0
        v.enabled = []
        v.candidates = 0
        return v
    v.max_candidates = len(cands)
    for i, ev in enumerate(trace.events):
        nxt, seen, enabled = [], set(), {}
        for st in cands:
            for action, succ in spec.successors(st):
                enabled.setdefault(action.name, action.location)
                if ev.action is not None and action.name != ev.action:
                    continue
                if not _obs_matches(succ, ev.vars):
                    continue
                k = _state_key(succ)
                if k not in seen:
                    seen.add(k)
                    nxt.append(succ)
        if not nxt:
            v.ok = False
            v.diverged_at = i
            v.enabled = sorted(enabled.items())
            v.candidates = len(cands)
            return v
        if len(nxt) > max_candidates:
            raise TLAError(
                f"trace {trace.tid}: candidate set exceeds "
                f"{max_candidates} at event {i} — the trace is too "
                f"weakly observed to validate within bounds")
        cands = nxt
        v.max_candidates = max(v.max_candidates, len(cands))
        if v.violated_invariant is None:
            bads = [spec.check_invariants(st) for st in cands]
            if all(b is not None for b in bads):
                # every state consistent with the observations so far
                # violates an invariant: the implementation is in a
                # certainly-bad (if spec-reachable) state — reported
                # as metadata, conformance checking continues
                v.violated_invariant = bads[0]
                v.violated_at = i
    return v


def divergence_record(trace, verdict):
    """The JSON-able divergence report (one stable shape shared with
    the batch validator's device-derived records)."""
    step = verdict.diverged_at
    ev = (trace.events[step].to_record()
          if step is not None and step < len(trace.events) else {})
    rec = {"trace": trace.tid, "step": int(step),
           "event": ev,
           "enabled": [{"action": a, "location": loc}
                       for a, loc in (verdict.enabled or [])],
           "candidates": int(verdict.candidates)}
    if step == 0 and verdict.candidates == 0 and not verdict.enabled:
        rec["reason"] = "no-init-state"
    if verdict.violated_invariant:
        rec["invariant"] = verdict.violated_invariant
        rec["invariant_step"] = verdict.violated_at
    return rec


def host_validate_batch(spec, traces, *, obs=None, log=None,
                        max_seconds=None,
                        max_candidates=4096) -> ValidateResult:
    """Validate a whole batch through the interpreter — the engine for
    specs without a device kernel (or with observations the codec
    cannot encode), and the semantic oracle the batch engine's tests
    compare against."""
    from ..obs import RunObserver
    obs = RunObserver.ensure(obs, "validate-host", spec, log=log)
    res = ValidateResult(batch=len(traces))
    t0 = time.time()
    obs.start(t0, backend="host")
    deadline = (t0 + max_seconds) if max_seconds else None
    for n, trace in enumerate(traces):
        verdict = validate_trace(spec, trace,
                                 max_candidates=max_candidates)
        res.traces_checked += 1
        if verdict.ok:
            res.accepted += 1
        else:
            rec = divergence_record(trace, verdict)
            res.divergences.append(rec)
            obs.divergence(trace.tid, verdict.diverged_at,
                           enabled=[e["action"] for e in rec["enabled"]],
                           candidates=rec["candidates"])
        if (n + 1) % 64 == 0 or n + 1 == len(traces):
            obs.validate_chunk(0, traces=res.traces_checked,
                               divergences=len(res.divergences))
            obs.progress(traces=res.traces_checked,
                         extra=f"{len(res.divergences)} divergence(s)")
        if deadline is not None and time.time() > deadline:
            res.error = "deadline"
            break
    # a deadline stop is an incomplete run, not a divergence —
    # res.error says so; ok mirrors the BFS time-budget contract
    res.ok = not res.divergences
    obs.gauge("divergences", len(res.divergences))
    return obs.finish(res)
