"""Batched trace validation on the device mesh (ISSUE 8 tentpole).

``BatchValidator`` is the production CI engine: thousands of recorded
implementation traces are checked against the compiled spec kernel
concurrently — per step the kernel expands every candidate state's
full successor set (``kern.step_all``), filters to successors
consistent with the recorded event (action id and/or encoded-leaf
observations), dedups by fingerprint, and keeps the surviving
candidates.  Traces are vmapped over the batch axis and shard_mapped
across a 1-D device mesh (walkers become traces — the ``sim/fleet``
idiom), steps run in fused chunks behind the ``engine/pipeline``
dispatch window, and a SIGTERM under a ``PreemptionGuard`` writes a
CRC'd rescue snapshot of the committed candidate frontier and raises
``Preempted`` (the exit-75 contract) which ``resume_from`` continues
bit-identically.

**Determinism contract.**  Every per-step op is elementwise over the
trace axis and reductions are integer psums, rounds cover contiguous
trace ranges in order, and the candidate dedup/truncation is a pure
first-occurrence scan in (candidate, lane) order — so the divergence
report of every trace (event index, candidate count, spec-side
enabled set) is bit-identical across mesh sizes, batch sizes, and
rescue/resume seams.

**Exactness.**  The candidate set is bounded by ``cand_cap`` slots
per trace.  A step producing more consistent successors than fit is
NOT silently truncated: the chunk reports overflow, the host doubles
the cap, recompiles, and redraws the round from step 0 (no RNG — the
redraw is exact), journaled as ``grow {what: "cand_cap"}``.  Message
-table overflow inside a successor redraws the same way.  Every
device-reported divergence is confirmed by the interpreter validator
(``host.validate_trace``) before it reaches the report — a
device/interpreter disagreement is a loud ``TLAError``, never a
wrong verdict (the fleet replay cross-check idiom).
"""

from __future__ import annotations

import hashlib
import json
import time

import numpy as np

import jax
import jax.numpy as jnp

from ..core.values import TLAError
from ..engine.checkpoint import spec_digest
from ..engine.pipeline import DispatchPipeline
from ..engine.spec import SpecModel
from ..exitcodes import (EX_OK, EX_RESUMABLE, EX_SOFTWARE,
                         EX_VIOLATION, job_state)
from ..models import registry
from ..obs import RunObserver, closes_observer
from ..resilience.faults import fault_point
from ..resilience.supervisor import (Outcome, Preempted,
                                     PreemptionGuard, is_oom,
                                     preempt_signal)
from ..sim.fleet import load_fleet_snapshot, save_fleet_snapshot
from .host import (ValidateResult, divergence_record, validate_trace)
from .traces import Trace  # noqa: F401 — the input type

I32 = jnp.int32


class ObservationUnsupported(TLAError):
    """The codec cannot express a trace observation as encoded-leaf
    comparisons — the caller should fall back to the interpreter
    validator (``host.host_validate_batch``)."""


def encode_obs(codec, tmpl, var, value):
    """Encode one pinned spec variable as ``{leaf_key: (mask, values)}``
    against the codec's state layout.  Codecs may provide their own
    ``encode_obs(var, value)`` hook; the default covers the common
    case of a scalar int/bool variable stored under its own leaf key
    (the stub codec, and any codec whose leaves are named after the
    variables they hold).  Anything else raises
    :class:`ObservationUnsupported`."""
    hook = getattr(codec, "encode_obs", None)
    if hook is not None:
        return hook(var, value)
    if var not in tmpl:
        raise ObservationUnsupported(
            f"codec {type(codec).__name__} has no leaf for variable "
            f"{var!r} and no encode_obs hook")
    leaf = tmpl[var]
    if not isinstance(value, (bool, int, np.integer)):
        raise ObservationUnsupported(
            f"variable {var!r}: only scalar int/bool observations are "
            f"encodable without a codec encode_obs hook "
            f"(got {type(value).__name__})")
    vals = np.full(leaf.shape, int(value), leaf.dtype)
    # an observation that does not round-trip through the leaf dtype
    # (2**40 wraps to 0 in int32, 2 to True in bool) would compare
    # equal to the WRONG encoded state — a silent false accept, the
    # one verdict the interpreter cross-check never sees
    if int(vals.flat[0]) != int(value):
        raise ObservationUnsupported(
            f"variable {var!r}: observation {value!r} does not fit "
            f"the encoded leaf dtype {leaf.dtype}")
    return {var: (np.ones(leaf.shape, bool), vals)}


def traces_digest(traces):
    """Identity of a trace batch — stamped into rescue snapshots so a
    resume against a different TRACE.jsonl is a policy error."""
    h = hashlib.sha1()
    for t in traces:
        h.update(json.dumps(t.to_record(), sort_keys=True).encode())
    return h.hexdigest()[:16]


VALIDATE_FORMAT = 1


class BatchValidator:
    """The sharded trace-validation engine (module docstring).

    ``batch`` traces run per round (padded to a multiple of the mesh
    size; pad slots never act); ``cand_cap`` is the per-trace
    candidate-set bound (grown on overflow); ``chunk_steps`` the fused
    step count per dispatch; ``pipeline`` the dispatch-window depth;
    ``confirm=False`` skips the per-divergence interpreter
    cross-check (benchmarks only — the default always confirms)."""

    def __init__(self, spec: SpecModel, batch=1024, n_devices=None,
                 mesh=None, chunk_steps=8, cand_cap=4, max_msgs=None,
                 pipeline=2, min_batch=8, max_retries=4,
                 model_factory=None, confirm=True, log=None):
        # trace validation tracks CONCRETE states: an observation may
        # pin any variable to a specific (model) value, so two
        # orbit-equivalent candidates are NOT interchangeable and
        # symmetry reduction never applies here (ISSUE 11: the default
        # kernel is built with fold_symmetry=False so orbit-folded
        # fingerprints can't merge distinct candidates; the CLI
        # rejects -symmetry on with -validate)
        self._model_factory = model_factory or (
            lambda spec, max_msgs=None: registry.make_model(
                spec, max_msgs=max_msgs, fold_symmetry=False))
        self.spec = spec
        self.inv_names = list(spec.cfg.invariants)
        self.chunk = int(chunk_steps)
        self.confirm = bool(confirm)
        self.min_batch = int(min_batch)
        self.max_retries = int(max_retries)
        self.pipeline = max(1, int(pipeline))
        self._log = log
        if cand_cap < 1:
            raise ValueError(f"cand_cap must be >= 1 (got {cand_cap})")
        self.K = int(cand_cap)
        if mesh is not None:
            self.mesh = mesh
            self.axis = mesh.axis_names[0]
            self._n_req = mesh.shape[self.axis]
        else:
            self.mesh = None
            self.axis = "d"
            self._n_req = n_devices     # None = every visible device
        self._max_msgs = max_msgs
        self._restore_batch = None   # requested batch, during a resume
        # pre-flight memo: (the checked batch, its digest) — by
        # reference, so run() on the same list skips both the encode
        # pass and the digest recompute
        self._obs_checked = (None, None)
        self._set_batch(int(batch))

    def log(self, msg):
        if self._log:
            self._log(f"validate: {msg}")

    # -- construction --------------------------------------------------
    def _set_batch(self, batch):
        """(Re)shape the engine for a round size: mesh, padding,
        recompile.  The OOM-degrade knob (batch halving)."""
        if batch < 1:
            raise ValueError(f"batch must be >= 1 (got {batch})")
        self.batch = int(batch)
        n = self._n_req or len(jax.devices())
        n = max(1, min(int(n), self.batch, len(jax.devices())))
        if self.mesh is None or self.mesh.shape[self.axis] != n:
            from jax.sharding import Mesh
            self.mesh = Mesh(np.array(jax.devices()[:n]), (self.axis,))
        self.D = self.mesh.shape[self.axis]
        self.T_pad = -(-self.batch // self.D) * self.D
        self._build(self._max_msgs)

    def _build(self, max_msgs):
        """Compile the fused validation-chunk kernel for the current
        (batch, mesh, cand_cap, message-table) shape."""
        from ..parallel.sharded_bfs import _shard_map
        self._max_msgs = max_msgs
        self.codec, self.kern = self._model_factory(self.spec,
                                                    max_msgs=max_msgs)
        kern = self.kern
        # leaf template: shapes/dtypes of one encoded state (also the
        # default encode_obs schema)
        st0 = next(iter(self.spec.init_states()))
        self._tmpl = {k: np.asarray(v)
                      for k, v in self.codec.encode(st0).items()}
        self._init_enc = None        # lazy cache of encoded init states
        lane_aid = jnp.asarray(kern.lane_action)
        L = int(lane_aid.shape[0])
        self.L = L
        K = self.K
        keys = sorted(self._tmpl)
        axis = self.axis
        n_steps = self.chunk

        def step_all_clean(st):
            succs, en = kern.step_all(st)
            return ({k: v for k, v in succs.items()
                     if not k.startswith("_")}, en)

        def one_trace(cs, al, da, de, dc, tl, aid1, m1, v1, s):
            """Advance one trace's candidate set through one event.
            cs: {k: [K, ...]}, al: [K], da/dc/tl: scalars, de: [L],
            aid1: scalar action obs, m1/v1: {k: leaf-shaped obs}."""
            active = (s < tl) & (da < 0)
            succs, en = jax.vmap(step_all_clean)(cs)   # [K, L, ...]
            en = en & al[:, None]
            ok = en & ((aid1 < 0) | (lane_aid == aid1))[None, :]
            for k in keys:
                eq = (succs[k] == v1[k]) | ~m1[k]
                ok = ok & eq.reshape(K, L, -1).all(-1)
            okf = ok.reshape(K * L)
            flat = {k: v.reshape((K * L,) + v.shape[2:])
                    for k, v in succs.items()}
            err1 = jnp.asarray(False)
            if "err" in flat:
                errf = flat["err"].reshape(K * L, -1).any(-1) \
                    if flat["err"].ndim > 1 else flat["err"] != 0
                err1 = active & (okf & errf).any()
                okf = okf & ~errf
            fp = jax.vmap(kern.fingerprint)(flat)      # [K*L, W]
            fp = fp.reshape(K * L, -1)
            same = (fp[:, None, :] == fp[None, :, :]).all(-1)
            dup = (jnp.tril(same, k=-1) & okf[None, :]).any(1)
            uniq = okf & ~dup
            n_new = uniq.sum(dtype=I32)
            rank = jnp.cumsum(uniq.astype(I32)) - 1
            keep = uniq & (rank < K)
            dest = jnp.where(keep, rank, K).astype(I32)
            new_c = {k: jnp.zeros((K,) + v.shape[1:], v.dtype)
                     .at[dest].set(v, mode="drop")
                     for k, v in flat.items()}
            new_al = jnp.zeros((K,), bool).at[dest].set(
                jnp.ones((K * L,), bool), mode="drop")
            ovf1 = active & (n_new > K)
            div_now = active & (n_new == 0)
            da = jnp.where(div_now, s, da)
            de = jnp.where(div_now, en.any(0), de)
            dc = jnp.where(div_now, al.sum(dtype=I32), dc)
            upd = active & (n_new > 0)
            cs = {k: jnp.where(upd, new_c[k], cs[k]) for k in cs}
            al = jnp.where(upd, new_al, al)
            return cs, al, da, de, dc, ovf1, err1

        def chunk_fn(cands, alive, div_at, div_en, div_cand, tlen,
                     aid_obs, ob_m, ob_v, step0):
            def step(carry, t):
                cands, alive, div_at, div_en, div_cand, ovf, err = carry
                s = (step0 + t).astype(I32)
                out = jax.vmap(one_trace,
                               in_axes=(0, 0, 0, 0, 0, 0, 0, 0, 0,
                                        None))(
                    cands, alive, div_at, div_en, div_cand, tlen,
                    aid_obs[:, t],
                    {k: v[:, t] for k, v in ob_m.items()},
                    {k: v[:, t] for k, v in ob_v.items()}, s)
                (cands, alive, div_at, div_en, div_cand,
                 ovf_t, err_t) = out
                return (cands, alive, div_at, div_en, div_cand,
                        ovf | ovf_t.any(), err | err_t.any()), None

            init = (cands, alive, div_at, div_en, div_cand,
                    jnp.asarray(False), jnp.asarray(False))
            (cands, alive, div_at, div_en, div_cand, ovf,
             err), _ = jax.lax.scan(step, init,
                                    jnp.arange(n_steps, dtype=I32))
            rem = jax.lax.psum(
                ((div_at < 0) & (tlen > step0 + n_steps))
                .sum(dtype=I32), axis)
            n_div = jax.lax.psum((div_at >= 0).sum(dtype=I32), axis)
            ovf_g = jax.lax.psum(ovf.astype(I32), axis) > 0
            err_g = jax.lax.psum(err.astype(I32), axis) > 0
            return (cands, alive, div_at, div_en, div_cand,
                    rem, n_div, ovf_g, err_g)

        from jax.sharding import PartitionSpec as P
        sp = P(self.axis)
        self._chunk = jax.jit(_shard_map(
            chunk_fn, self.mesh,
            in_specs=(sp, sp, sp, sp, sp, sp, sp, sp, sp, P()),
            out_specs=(sp, sp, sp, sp, sp, P(), P(), P(), P())))
        self._fresh_jit = True

    # -- host-side encoding --------------------------------------------
    def _init_states_enc(self):
        """Interpreter init states + their encodings, computed once per
        build (the fleet ``_init_batch`` caching idiom)."""
        if self._init_enc is None:
            states = list(self.spec.init_states())
            self._init_enc = (states,
                              [{k: np.asarray(v) for k, v in
                                self.codec.encode(st).items()}
                               for st in states])
        return self._init_enc

    def check_observations(self, traces):
        """Fail fast (ObservationUnsupported) if any observation in
        `traces` cannot be encoded against this codec — so the caller
        can fall back to the host validator before any device time.
        A passed batch is memoized (with its digest) so :meth:`run`
        on the same list pays neither the O(traces x events) encode
        pass nor the digest serialization a second time."""
        for t in traces:
            for k, v in t.init.items():
                encode_obs(self.codec, self._tmpl, k, v)
            for ev in t.events:
                if ev.action is not None and \
                        ev.action not in self.kern.action_names:
                    raise TLAError(
                        f"trace {t.tid}: action {ev.action!r} has no "
                        f"kernel lane (spec actions: "
                        f"{self.kern.action_names})")
                for k, v in ev.vars.items():
                    encode_obs(self.codec, self._tmpl, k, v)
        self._obs_checked = (traces, traces_digest(traces))

    def _encode_round(self, rtraces):
        """Host arrays for one round: initial candidate sets, event
        observation planes, lengths.  Returns ``(arrays, pre_div, S)``
        where ``pre_div[i]`` is a host-side verdict for traces whose
        init observation matches NO init state (they never reach the
        device), and S the padded step count.  May grow ``cand_cap``
        first when an init candidate set alone exceeds it."""
        from .host import _obs_matches
        states, encs = self._init_states_enc()
        T, K = self.T_pad, self.K
        init_sets = []
        for t in rtraces:
            idxs = [j for j, st in enumerate(states)
                    if _obs_matches(st, t.init)]
            init_sets.append(idxs)
        need = max([len(x) for x in init_sets] or [1])
        if need > K:
            while self.K < need:
                self.K *= 2
            self.log(f"init candidate sets need {need} slots; growing "
                     f"cand_cap to {self.K}")
            self._build(self._max_msgs)
            states, encs = self._init_states_enc()
            K = self.K
        S = max([len(t.events) for t in rtraces] or [0])
        S = max(S, 1)
        cands = {k: np.zeros((T, K) + v.shape, v.dtype)
                 for k, v in self._tmpl.items()}
        alive = np.zeros((T, K), bool)
        tlen = np.zeros((T,), np.int32)
        aid_obs = np.full((T, S), -1, np.int32)
        ob_m = {k: np.zeros((T, S) + v.shape, bool)
                for k, v in self._tmpl.items()}
        ob_v = {k: np.zeros((T, S) + v.shape, v.dtype)
                for k, v in self._tmpl.items()}
        pre_div = {}
        for i, t in enumerate(rtraces):
            if not init_sets[i]:
                pre_div[i] = True     # host-reported: no init state
                continue
            tlen[i] = len(t.events)
            for c, j in enumerate(init_sets[i]):
                for k in cands:
                    cands[k][i, c] = encs[j][k]
                alive[i, c] = True
            for s, ev in enumerate(t.events):
                if ev.action is not None:
                    aid_obs[i, s] = self.kern.action_names.index(
                        ev.action)
                for var, val in ev.vars.items():
                    for k, (m, v) in encode_obs(
                            self.codec, self._tmpl, var, val).items():
                        ob_m[k][i, s] |= np.asarray(m, bool)
                        ob_v[k][i, s] = np.where(
                            np.asarray(m, bool), v, ob_v[k][i, s])
        arrays = {"cands": cands, "alive": alive, "tlen": tlen,
                  "aid_obs": aid_obs, "ob_m": ob_m, "ob_v": ob_v}
        return arrays, pre_div, S

    # -- rescue/resume -------------------------------------------------
    def _rescue(self, path, *, base, active, step, committed, res,
                digest, chunks, obs, extra=None):
        sig = preempt_signal() or "SIGTERM"
        manifest = {
            "spec_digest": spec_digest(self.spec),
            "traces_digest": digest,
            "base": int(base), "active": int(active),
            "step": int(step), "chunks": int(chunks),
            "batch": int(self.batch), "cand_cap": int(self.K),
            "max_msgs": (int(self.codec.shape.MAX_MSGS)
                         if getattr(self.codec, "shape", None)
                         is not None else None),
            "traces": int(res.traces_checked),
            "accepted": int(res.accepted),
            # snapshot_info-compat keys (the service rescue handoff)
            "depth": int(step), "fp_count": int(base),
            "elapsed": float(obs.elapsed()),
            "extra": dict(extra or {},
                          divergences=res.divergences),
        }
        arrays = None
        if path:
            cands, alive, div_at, div_en, div_cand = committed
            ca = {f"c_{k}": np.asarray(jax.device_get(v))
                  for k, v in cands.items()}
            ca["alive"] = np.asarray(jax.device_get(alive))
            ca["div_at"] = np.asarray(jax.device_get(div_at))
            ca["div_en"] = np.asarray(jax.device_get(div_en))
            ca["div_cand"] = np.asarray(jax.device_get(div_cand))
            arrays = {"walkers.npz": ca}
            save_fleet_snapshot(path, manifest=manifest,
                                arrays=arrays, kind="validate")
        obs.rescue(path or "", step, base, sig)
        self.log(f"preempted by {sig}: candidate frontier rescued at "
                 f"step {step} of the round at base {base}")
        return Preempted(path, step, base, sig)

    def _load_resume(self, path, digest):
        manifest, arrays = load_fleet_snapshot(
            path, expect_digest=spec_digest(self.spec),
            kind="validate")
        if manifest.get("traces_digest") != digest:
            raise ValueError(
                f"{path}: snapshot was written for a different trace "
                f"batch (digest {manifest.get('traces_digest')}, this "
                f"run {digest}); refusing to resume")
        if int(manifest["cand_cap"]) != self.K \
                or int(manifest["batch"]) != self.batch \
                or manifest.get("max_msgs") != (
                    int(self.codec.shape.MAX_MSGS)
                    if getattr(self.codec, "shape", None) is not None
                    else None):
            if int(manifest["batch"]) != self.batch:
                # the rescued round must finish at the snapshot's
                # batch; rounds after it rescale back to the requested
                # one (the elastic --batch-per-device contract)
                self._restore_batch = self.batch
            self.K = int(manifest["cand_cap"])
            self._max_msgs = manifest.get("max_msgs")
            self._set_batch(int(manifest["batch"]))

        def fit(a, fill):
            # re-pad the rescued rows to this mesh's T_pad: live traces
            # occupy rows [0, active) and active <= batch <= every
            # T_pad, so added/dropped rows are always dead pad slots
            a = np.asarray(a)
            if a.shape[0] > self.T_pad:
                return a[:self.T_pad]
            if a.shape[0] < self.T_pad:
                pad = np.full((self.T_pad - a.shape[0],) + a.shape[1:],
                              fill, a.dtype)
                return np.concatenate([a, pad], axis=0)
            return a

        ca = arrays.get("walkers.npz", {})
        resume = None
        if int(manifest["step"]) > 0 and ca:
            resume = {
                "step": int(manifest["step"]),
                "cands": {k[2:]: fit(ca[k], 0) for k in ca
                          if k.startswith("c_")},
                "alive": fit(ca["alive"], False),
                "div_at": fit(ca["div_at"], -1),
                "div_en": fit(ca["div_en"], False),
                "div_cand": fit(ca["div_cand"], 0)}
        return manifest, resume

    # -- one round -----------------------------------------------------
    def _run_round(self, rtraces, *, base, obs, checkpoint_path,
                   on_chunk, chunks_before, res, digest, deadline,
                   resume=None, rescue_extra=None):
        """Validate one round of traces to completion, redrawing from
        step 0 on a growth event (candidate cap / message table — no
        RNG, so the redraw is exact).  Returns
        ``(div_at, div_en, div_cand, pre_div, chunks, stopped)``."""
        while True:                       # growth-redraw loop
            arrays, pre_div, S = self._encode_round(rtraces)
            if resume is not None:
                step = int(resume["step"])
                committed = (
                    {k: jnp.asarray(v)
                     for k, v in resume["cands"].items()},
                    jnp.asarray(resume["alive"]),
                    jnp.asarray(resume["div_at"]),
                    jnp.asarray(resume["div_en"]),
                    jnp.asarray(resume["div_cand"]))
                resume = None
            else:
                step = 0
                committed = (
                    {k: jnp.asarray(v)
                     for k, v in arrays["cands"].items()},
                    jnp.asarray(arrays["alive"]),
                    jnp.full((self.T_pad,), -1, np.int32),
                    jnp.zeros((self.T_pad, self.L), bool),
                    jnp.zeros((self.T_pad,), np.int32))
            status, committed, chunks_before, stopped = \
                self._round_chunks(
                    arrays, committed, step, S, base=base, obs=obs,
                    checkpoint_path=checkpoint_path,
                    on_chunk=on_chunk, chunks_before=chunks_before,
                    res=res, digest=digest, deadline=deadline,
                    active=len(rtraces), rescue_extra=rescue_extra)
            if status == "done":
                break
        div_at = np.asarray(jax.device_get(committed[2]))
        div_en = np.asarray(jax.device_get(committed[3]))
        div_cand = np.asarray(jax.device_get(committed[4]))
        return div_at, div_en, div_cand, pre_div, chunks_before, stopped

    def _round_chunks(self, arrays, committed, step, S, *, base, obs,
                      checkpoint_path, on_chunk, chunks_before, res,
                      digest, deadline, active, rescue_extra):
        """The chunked dispatch loop of one round.  Returns
        ``(status, committed, chunks, stopped)`` where status is
        ``"done"`` (round finished / deadline-stopped) or ``"grown"``
        (a growth happened — the caller re-encodes and redraws)."""
        tlen = jnp.asarray(arrays["tlen"])
        pipe = DispatchPipeline(self.pipeline, obs,
                                ready=lambda out: out[5])
        launched = step
        cur = committed
        chunk_idx = chunks_before
        stopped = False

        def pull(out):
            return jax.device_get((out[5], out[6], out[7], out[8]))

        try:
            while step < S:
                while pipe.has_room() and launched < S:
                    aid = ev_slice_d(arrays, "aid_obs", launched,
                                     self.chunk, self.T_pad, -1)
                    m_sl = {k: ev_slice_d(arrays["ob_m"], k, launched,
                                          self.chunk, self.T_pad,
                                          False)
                            for k in arrays["ob_m"]}
                    v_sl = {k: ev_slice_d(arrays["ob_v"], k, launched,
                                          self.chunk, self.T_pad, 0)
                            for k in arrays["ob_v"]}
                    out = pipe.launch(
                        self._chunk, cur[0], cur[1], cur[2], cur[3],
                        cur[4], tlen, aid, m_sl, v_sl,
                        jnp.asarray(launched, I32),
                        fresh=self._fresh_jit,
                        label=f"validate chunk (step {launched})")
                    self._fresh_jit = False
                    cur = (out[0], out[1], out[2], out[3], out[4])
                    launched += self.chunk
                out, sc = pipe.collect(pull)
                rem, n_div, ovf, err = sc
                if bool(err):
                    pipe.drain()
                    old = self.codec.shape.MAX_MSGS
                    self._build(old * 2)
                    obs.grow("message_table", self.codec.shape.MAX_MSGS)
                    self.log(f"message table grown to "
                             f"{self.codec.shape.MAX_MSGS} slots; "
                             f"redrawing the round")
                    return "grown", committed, chunk_idx, False
                if bool(ovf):
                    pipe.drain()
                    self.K *= 2
                    self._build(self._max_msgs)
                    obs.grow("cand_cap", self.K)
                    self.log(f"candidate set overflowed; cand_cap "
                             f"grown to {self.K}; redrawing the round")
                    return "grown", committed, chunk_idx, False
                committed = (out[0], out[1], out[2], out[3], out[4])
                step = min(step + self.chunk, S)
                chunk_idx += 1
                fault_point("level", depth=chunk_idx, obs=obs)
                # rem/n_div are exact in-round counts, so both
                # counters stay cumulative-across-the-run mid-round
                # (SCHEMA.md contract; the host validator's rows agree)
                obs.validate_chunk(step,
                                   traces=(res.traces_checked
                                           + active - int(rem)),
                                   divergences=(len(res.divergences)
                                                + int(n_div)),
                                   active=int(rem), base=int(base))
                if on_chunk is not None:
                    on_chunk(step)
                if preempt_signal() is not None:
                    pipe.drain()
                    raise self._rescue(
                        checkpoint_path, base=base, active=active,
                        step=step, committed=committed, res=res,
                        digest=digest, chunks=chunk_idx, obs=obs,
                        extra=rescue_extra)
                if int(rem) == 0:
                    pipe.drain()
                    break
                if deadline is not None and time.time() > deadline:
                    pipe.drain()
                    stopped = True
                    break
        finally:
            pipe.drain()
        return "done", committed, chunk_idx, stopped

    # -- divergence reporting ------------------------------------------
    def _enabled_from_lanes(self, mask):
        """Device lane mask -> the sorted spec-side enabled set with
        action/location metadata — aggregated to the ACTION level so
        the record is byte-identical to the interpreter validator's
        ``divergence_record`` shape (one stable report shape across
        both engines; lane params are a device-layout detail)."""
        names = self.kern.action_names
        la = np.asarray(self.kern.lane_action)
        locs = {a.name: a.location for a in self.spec.actions}
        seen = sorted({names[int(la[ln])]
                       for ln in np.nonzero(np.asarray(mask))[0]})
        return [{"action": a, "location": locs.get(a)} for a in seen]

    def _commit_round(self, res, rtraces, div_at, div_en, div_cand,
                      pre_div, obs):
        for i, t in enumerate(rtraces):
            res.traces_checked += 1
            if i in pre_div:
                verdict = validate_trace(self.spec, t)
                rec = divergence_record(t, verdict)
                res.divergences.append(rec)
                obs.divergence(t.tid, rec["step"],
                               candidates=rec["candidates"])
                continue
            if div_at[i] < 0:
                res.accepted += 1
                continue
            step = int(div_at[i])
            ev = (t.events[step].to_record()
                  if step < len(t.events) else {})
            rec = {"trace": t.tid, "step": step, "event": ev,
                   "enabled": self._enabled_from_lanes(div_en[i]),
                   "candidates": int(div_cand[i])}
            if self.confirm:
                verdict = validate_trace(self.spec, t)
                if verdict.ok or verdict.diverged_at != step:
                    raise TLAError(
                        f"device/interpreter divergence: the batch "
                        f"validator reports trace {t.tid} diverging "
                        f"at event {step}, but the interpreter says "
                        f"{'accepted' if verdict.ok else f'event {verdict.diverged_at}'}")
                if verdict.violated_invariant:
                    rec["invariant"] = verdict.violated_invariant
                    rec["invariant_step"] = verdict.violated_at
            res.divergences.append(rec)
            obs.divergence(t.tid, step,
                           enabled=[e["action"] for e in rec["enabled"]],
                           candidates=rec["candidates"])

    # -- the entry -----------------------------------------------------
    @closes_observer
    def run(self, traces, *, checkpoint_path=None, resume_from=None,
            obs=None, log=None, max_seconds=None,
            on_chunk=None) -> ValidateResult:
        """Validate `traces` (a list of :class:`Trace`) in rounds of
        ``batch``; returns a :class:`ValidateResult` whose
        ``divergences`` records are bit-identical across mesh sizes,
        batch sizes and rescue/resume seams (module docstring)."""
        if log is not None:
            self._log = self._log or log
        obs = RunObserver.ensure(obs, "validate", self.spec, log=log)
        self._obs_active = obs
        if self._obs_checked[0] is not traces:
            self.check_observations(traces)
        digest = self._obs_checked[1]
        res = ValidateResult(batch=self.batch)
        t0 = time.time()
        base = 0
        round_active = None
        chunks = 0
        resume = None
        if resume_from:
            manifest, resume = self._load_resume(resume_from, digest)
            base = int(manifest["base"])
            round_active = int(manifest["active"])
            chunks = int(manifest.get("chunks", 0))
            res.traces_checked = int(manifest.get("traces", 0))
            res.accepted = int(manifest.get("accepted", 0))
            res.divergences = list(
                (manifest.get("extra") or {}).get("divergences") or [])
            res.batch = self.batch
            t0 -= float(manifest["elapsed"])
        obs.start(t0, backend=jax.default_backend(),
                  resumed=resume_from is not None)
        obs.gauge("mesh_devices", self.D)
        obs.gauge("pipeline_depth", self.pipeline)
        obs.gauge("cand_cap", self.K)
        obs.gauge("validate_batch", self.batch)
        deadline = (t0 + max_seconds) if max_seconds else None
        retries = 0
        while base < len(traces):
            active = (round_active if round_active is not None
                      else min(self.batch, len(traces) - base))
            round_active = None
            rtraces = traces[base:base + active]
            try:
                (div_at, div_en, div_cand, pre_div, chunks,
                 stopped) = self._run_round(
                    rtraces, base=base, obs=obs,
                    checkpoint_path=checkpoint_path,
                    on_chunk=on_chunk, chunks_before=chunks, res=res,
                    digest=digest, deadline=deadline, resume=resume)
            except Preempted:
                raise
            except Exception as e:  # noqa: BLE001 — OOM ladder below
                resume = None
                self._restore_batch = None   # the degrade wins
                if not self._try_degrade_oom(e, retries, obs):
                    raise
                retries += 1
                continue
            resume = None
            if stopped:
                # deadline-cut round: its traces did NOT finish — do
                # not report them (a half-scanned trace is neither
                # accepted nor diverged)
                res.error = "deadline"
                break
            self._commit_round(res, rtraces, div_at, div_en, div_cand,
                               pre_div, obs)
            base += active
            if self._restore_batch is not None:
                if self._restore_batch != self.batch:
                    self._set_batch(self._restore_batch)
                    res.batch = self.batch
                    obs.gauge("validate_batch", self.batch)
                    self.log(f"rescued round committed; batch rescaled "
                             f"to the requested {self.batch}")
                self._restore_batch = None
            obs.progress(traces=res.traces_checked,
                         extra=f"{len(res.divergences)} divergence(s)")
        # a deadline stop is an incomplete run, not a divergence —
        # res.error says so; ok mirrors the BFS time-budget contract
        res.ok = not res.divergences
        obs.gauge("divergences", len(res.divergences))
        obs.gauge("cand_cap", self.K)
        return obs.finish(res)

    def _try_degrade_oom(self, e, retries, obs):
        """The validator's OOM ladder: halve the round batch (fewer
        traces resident per dispatch) and redraw — per-trace results
        are independent of round boundaries, so the degraded run's
        report is unchanged."""
        from ..resilience.faults import InjectedFault
        if not is_oom(e) or retries >= self.max_retries \
                or self.batch // 2 < self.min_batch:
            return False
        if not isinstance(e, InjectedFault):
            obs.fault("oom", "level")
        old = self.batch
        self._set_batch(self.batch // 2)
        obs.degrade("validate_batch", old, self.batch)
        obs.retry(retries + 1, 0.0)
        obs.gauge("validate_batch", self.batch)
        self.log(f"OOM ({e}): halving the round batch {old} -> "
                 f"{self.batch} traces and redrawing")
        return True


def ev_slice_d(src, key, s0, chunk, t_pad, fill):
    """Slice one observation plane ``src[key][:, s0:s0+chunk]``,
    padded to the chunk width (steps beyond the round's last event are
    unobserved and inactive anyway — ``tlen`` gates them)."""
    sl = src[key][:, s0:s0 + chunk]
    if sl.shape[1] < chunk:
        pad_shape = (t_pad, chunk - sl.shape[1]) + sl.shape[2:]
        sl = np.concatenate([sl, np.full(pad_shape, fill, sl.dtype)],
                            axis=1)
    return sl


def batch_validate(spec, traces, *, batch=1024, n_devices=None,
                   chunk_steps=8, cand_cap=4, max_msgs=None,
                   pipeline=2, confirm=True, model_factory=None,
                   checkpoint_path=None, resume_from=None, obs=None,
                   log=None, max_seconds=None) -> ValidateResult:
    """One-call batched validation (the CLI ``-validate`` engine)."""
    bv = BatchValidator(spec, batch=batch, n_devices=n_devices,
                        chunk_steps=chunk_steps, cand_cap=cand_cap,
                        max_msgs=max_msgs, pipeline=pipeline,
                        confirm=confirm, model_factory=model_factory,
                        log=log)
    return bv.run(traces, checkpoint_path=checkpoint_path,
                  resume_from=resume_from, obs=obs, log=log,
                  max_seconds=max_seconds)


def validate_result_summary(res):
    """ValidateResult -> the JSON-able summary stored on a service
    job."""
    return {"ok": bool(res.ok), "traces": int(res.traces_checked),
            "accepted": int(res.accepted),
            "divergences": list(res.divergences or []),
            "first_divergence": res.first_divergence,
            "error": res.error,
            "elapsed_s": round(float(res.elapsed or 0.0), 3)}


def run_validate_job(spec, traces, *, checkpoint_path=None,
                     journal_path=None, metrics_path=None, log=None,
                     observer_factory=None, **kwargs) -> Outcome:
    """The worker-process entry for ``kind="validate"`` jobs — the
    validation twin of ``sim.hunt.run_hunt_job``: run a batch
    validation under a PreemptionGuard and reify every ending as an
    :class:`Outcome` through the one exit-code table:

    * every trace accepted            -> ``done`` (EX_OK)
    * divergences found               -> ``violated`` (EX_VIOLATION)
    * SIGTERM/cancel/scheduler tick   -> ``preempted-requeued``
      (EX_RESUMABLE) with the candidate-frontier rescue attached
    * anything else                   -> ``failed`` (EX_SOFTWARE)

    Unencodable observations fall back to the interpreter validator
    (the CLI idiom): pre-flighted BEFORE the journal-backed observer
    is handed over, so the fallback run still writes the job's
    journal/metrics through the same observer.
    """
    from .host import host_validate_batch
    factory = observer_factory or RunObserver
    obs = factory(journal_path=journal_path,
                  metrics_path=metrics_path, log=log)
    summary = {"engine": "validate", "traces": len(traces)}
    run_kw = {k: kwargs.pop(k) for k in ("resume_from", "max_seconds")
              if k in kwargs}
    try:
        with PreemptionGuard(log=log):
            bv = None
            try:
                bv = BatchValidator(spec, log=log, **kwargs)
                bv.check_observations(traces)
            except ObservationUnsupported as e:
                if log:
                    log(f"{e}; falling back to the interpreter "
                        f"validator")
                res = host_validate_batch(
                    spec, traces, obs=obs, log=log,
                    max_seconds=run_kw.get("max_seconds"))
                bv = None
            if bv is not None:
                res = bv.run(traces, checkpoint_path=checkpoint_path,
                             obs=obs, log=log, **run_kw)
    except Preempted as p:
        return Outcome(
            state=job_state(EX_RESUMABLE), exit_code=EX_RESUMABLE,
            rescue={"path": p.path, "depth": p.depth,
                    "distinct": p.distinct, "signal": p.signal},
            summary=summary)
    except Exception as e:  # noqa: BLE001 — reified, not swallowed
        return Outcome(state=job_state(EX_SOFTWARE),
                       exit_code=EX_SOFTWARE,
                       error=f"{type(e).__name__}: {e}",
                       summary=summary)
    summary["traces"] = res.traces_checked
    summary["divergences"] = len(res.divergences or [])
    code = EX_OK if res.ok else EX_VIOLATION
    return Outcome(state=job_state(code), exit_code=code, result=res,
                   summary=summary)
