"""tpuvsr/validate — batched trace validation (ISSUE 8).

Check recorded implementation traces (TRACE.jsonl) against the spec:
per step the next-state relation is constrained to transitions
consistent with the recorded event (arxiv 2404.16075), partial
observations tracked as candidate-state sets.  ``host`` is the
interpreter reference engine; ``batch`` the vmapped/shard_mapped
production engine with pipeline dispatch, rescue checkpoints and
exit-75 resume.  The CLI flag is ``-validate TRACES.jsonl``; the
dispatch service runs ``kind="validate"`` jobs.

This package's top-level imports stay jax-free (``traces``/``host``)
so the service's fast verbs can reach the summary helpers; importing
``BatchValidator``/``batch_validate``/``run_validate_job`` pulls in
jax lazily via ``tpuvsr.validate.batch``.
"""

from .host import (HostVerdict, ValidateResult, divergence_record,
                   host_validate_batch, validate_trace)
from .traces import (Trace, TraceEvent, load_traces,
                     record_from_entries, save_traces,
                     trace_from_record, traces_from_records)

__all__ = [
    "HostVerdict", "ValidateResult", "divergence_record",
    "host_validate_batch", "validate_trace",
    "Trace", "TraceEvent", "load_traces", "record_from_entries",
    "save_traces", "trace_from_record", "traces_from_records",
    "BatchValidator", "ObservationUnsupported", "batch_validate",
    "run_validate_job", "validate_result_summary",
]


def __getattr__(name):
    if name in ("BatchValidator", "ObservationUnsupported",
                "batch_validate", "run_validate_job",
                "validate_result_summary", "traces_digest"):
        from . import batch
        return getattr(batch, name)
    raise AttributeError(f"module {__name__!r} has no attribute "
                         f"{name!r}")
