"""TRACE.jsonl: the recorded-implementation-trace format (ISSUE 8).

One JSON object per line, one object per trace:

    {"trace": "t-0007",
     "init": {"x": "0", "y": "0"},
     "events": [{"action": "IncX", "vars": {"x": "1"}},
                {"vars": {"y": "1"}},
                {}]}

* ``trace`` — the trace id (optional; defaults to the line index);
* ``init``  — a PARTIAL observation of the initial state: the spec
  init states consistent with it form the starting candidate set
  (omitted/empty = every init state);
* ``events`` — one recorded event per implementation step.  Each may
  pin the ``action`` name and/or a partial ``vars`` assignment of the
  post-state; anything unpinned is unobserved, and the validator
  tracks every spec state consistent with the observations (the
  nondeterminism handling of arxiv 2404.16075).

Values are JSON ints/bools, or strings holding TLA+ expressions
(parsed and evaluated against the spec's constants, so model values
and structured values round-trip through ``core.values.fmt``).  This
module is the one place the format is read or written; the host and
batch validators both consume :class:`Trace` objects.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from ..core.values import TLAError, fmt


@dataclass
class TraceEvent:
    action: str = None     # recorded action name (None = unobserved)
    vars: dict = field(default_factory=dict)   # partial post-state

    def to_record(self):
        out = {}
        if self.action is not None:
            out["action"] = self.action
        if self.vars:
            out["vars"] = {k: fmt(v) for k, v in sorted(self.vars.items())}
        return out


@dataclass
class Trace:
    tid: str
    events: list                        # [TraceEvent, ...]
    init: dict = field(default_factory=dict)  # partial init observation

    def to_record(self):
        out = {"trace": self.tid,
               "events": [e.to_record() for e in self.events]}
        if self.init:
            out["init"] = {k: fmt(v)
                           for k, v in sorted(self.init.items())}
        return out


def _value_env(spec):
    """Model-value members of cfg-bound sets, bound by name (the
    trace_parse idiom) so trace expressions mentioning them evaluate."""
    from ..core.values import ModelValue
    from ..interp.evalr import EMPTY_ENV
    extra = {}
    for val in spec.cfg.constants.values():
        if isinstance(val, frozenset):
            for m in val:
                if isinstance(m, ModelValue):
                    extra[m.name] = m
    return EMPTY_ENV.extend(extra)


def _parse_value(spec, env, raw, where):
    if isinstance(raw, bool) or isinstance(raw, int):
        return raw
    if isinstance(raw, str):
        from ..frontend.parser import parse_expr_text
        from ..interp.evalr import EvalCtx
        try:
            return spec.ev.eval(parse_expr_text(raw), env, EvalCtx({}))
        except Exception as e:  # noqa: BLE001 — rewrap with location
            raise TLAError(f"{where}: cannot evaluate value {raw!r}: "
                           f"{type(e).__name__}: {e}")
    raise TLAError(f"{where}: unsupported value {raw!r} "
                   f"(use an int, a bool, or a TLA+ expression string)")


def _check_names(spec, trace):
    """A trace naming a variable or action the spec doesn't have must
    fail loudly, not vacuously accept (the trace_parse contract)."""
    varnames = set(spec.module.variables)
    actnames = {a.name for a in spec.actions}
    for k in trace.init:
        if k not in varnames:
            raise TLAError(f"trace {trace.tid}: init observation binds "
                           f"variable {k!r} unknown to the spec")
    for i, ev in enumerate(trace.events):
        if ev.action is not None and ev.action not in actnames:
            raise TLAError(f"trace {trace.tid} event {i}: action "
                           f"{ev.action!r} is not a spec action")
        for k in ev.vars:
            if k not in varnames:
                raise TLAError(f"trace {trace.tid} event {i}: binds "
                               f"variable {k!r} unknown to the spec")


def trace_from_record(rec, spec, default_tid=None):
    """One TRACE.jsonl object -> :class:`Trace` (values evaluated)."""
    if not isinstance(rec, dict):
        raise TLAError(f"trace record is {type(rec).__name__}, "
                       f"not an object")
    env = _value_env(spec)
    tid = str(rec.get("trace", default_tid if default_tid is not None
                      else "t-0"))
    init = {k: _parse_value(spec, env, v, f"trace {tid} init.{k}")
            for k, v in (rec.get("init") or {}).items()}
    events = []
    for i, ev in enumerate(rec.get("events") or []):
        if not isinstance(ev, dict):
            raise TLAError(f"trace {tid} event {i}: not an object")
        act = ev.get("action")
        if act == spec.next_name:
            # the composite next-state relation names no concrete
            # action: a recorded "Next" pins nothing — normalize to
            # action-unobserved so both validators treat it alike
            act = None
        events.append(TraceEvent(
            action=act,
            vars={k: _parse_value(spec, env, v,
                                  f"trace {tid} event {i}.{k}")
                  for k, v in (ev.get("vars") or {}).items()}))
    t = Trace(tid=tid, events=events, init=init)
    _check_names(spec, t)
    return t


def traces_from_records(records, spec):
    return [trace_from_record(r, spec, default_tid=f"t-{i:04d}")
            for i, r in enumerate(records)]


def load_traces(path, spec):
    """Parse + validate a TRACE.jsonl file into a list of Traces."""
    out = []
    with open(path) as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError as e:
                raise TLAError(f"{path}:{i + 1}: not JSON: {e}")
            out.append(trace_from_record(rec, spec,
                                         default_tid=f"t-{i:04d}"))
    return out


def save_traces(path, records):
    """Write TRACE.jsonl records (dicts or Trace objects)."""
    with open(path, "w") as f:
        for r in records:
            if isinstance(r, Trace):
                r = r.to_record()
            f.write(json.dumps(r, sort_keys=True) + "\n")


def record_from_entries(entries, tid="t-0", drop_vars=(),
                        blank_every=None):
    """A TRACE.jsonl record from a ``TraceEntry`` list (a replayed
    counterexample, or a parsed reference trace dump) — the round-trip
    used by ``scripts/validate_demo.py``: a checker-produced trace is
    by construction spec-consistent, so validating it must accept.

    ``drop_vars`` removes variables from every observation (partial
    observation); ``blank_every=k`` blanks every k-th event entirely
    (action and vars — the fully-unobserved step that makes the
    candidate set grow)."""
    drop = set(drop_vars)
    init = {k: fmt(v) for k, v in sorted(entries[0].state.items())
            if k not in drop}
    events = []
    for n, e in enumerate(entries[1:]):
        if blank_every and (n + 1) % blank_every == 0:
            events.append({})
            continue
        ev = {"vars": {k: fmt(v) for k, v in sorted(e.state.items())
                       if k not in drop}}
        if e.action_name:
            ev["action"] = e.action_name
        if not ev["vars"]:
            del ev["vars"]
        events.append(ev)
    return {"trace": tid, "init": init, "events": events}
