"""Service CLI verbs: ``serve`` / ``submit`` / ``status`` /
``cancel`` / ``telemetry``.

The query surface of the dispatch service is deliberately thin: the
queue spool IS the database and each job's journal + metrics doc ARE
its API records — these verbs only fold and print them.

    python -m tpuvsr submit SPEC.tla [-config F] [--engine E]
                     [--priority N] [--devices N] [--tenant T] ...
    python -m tpuvsr serve  [--spool DIR] [--drain] [--devices N]
                     [--workers N] [--http PORT] [--tenant-weight T=W]
    python -m tpuvsr status [JOB] [--spool DIR] [--json] [--tail N]
    python -m tpuvsr cancel JOB [--spool DIR]
    python -m tpuvsr telemetry [SPOOL] [--watch] [--json | --prom]

``telemetry`` (ISSUE 17) folds the spool's journals through
:class:`tpuvsr.obs.telemetry.TelemetryAggregator` and prints the
fleet view — per-tenant latency histograms, DRR fairness vs actual
device-seconds, worker utilization, throughput windows, SLO breaches.
``--watch`` repolls on an interval; ``--prom`` prints the Prometheus
text exposition the HTTP front serves at ``GET /v1/metrics``.

``submit`` / ``status`` / ``cancel`` import neither jax nor the
engines — they are milliseconds against a live spool.  ``serve``
hosts a :class:`tpuvsr.service.worker.Worker` (one process, many
jobs); ``--drain`` exits when nothing is claimable (the smoke/demo
mode), without it the worker polls for new submissions until
``--max-seconds``.  The serving tier (ISSUE 14, ``tpuvsr/serve``)
rides the same verb: ``--workers N`` spawns N worker processes over
the shared spool (the parent supervises + sweeps stale claims),
``--http PORT`` raises the wire API (submit/status/cancel/list +
chunked journal streaming; ``--workers 0`` = front only), and the
fair-share knobs (``--tenant-weight``, ``--age-every``) shape the
deficit-round-robin pop order.

The spool location resolves as ``--spool`` > ``TPUVSR_SPOOL`` >
``./.tpuvsr-spool``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from ..exitcodes import EX_USAGE
from .queue import JobQueue, QueueError

VERBS = ("serve", "submit", "status", "cancel", "telemetry")


def default_spool():
    return os.environ.get("TPUVSR_SPOOL", ".tpuvsr-spool")


def _flag_pairs(items):
    """--flag KEY=VALUE (repeatable) -> dict, values parsed as JSON
    scalars when possible."""
    out = {}
    for item in items or []:
        if "=" not in item:
            raise ValueError(f"--flag wants KEY=VALUE, got {item!r}")
        k, v = item.split("=", 1)
        try:
            out[k] = json.loads(v)
        except ValueError:
            out[k] = v
    return out


def build_parser():
    p = argparse.ArgumentParser(
        prog="tpuvsr", description="verification dispatch service")
    sub = p.add_subparsers(dest="verb", required=True)

    sp = sub.add_parser("submit", help="enqueue a verification job")
    sp.add_argument("spec", nargs="?", default=None,
                    help="path to the .tla module (omit with --stub)")
    sp.add_argument("-config", "--config", default=None)
    sp.add_argument("--engine", default="auto",
                    choices=["auto", "device", "paged", "sharded"])
    sp.add_argument("--priority", type=int, default=0)
    sp.add_argument("--tenant", default=None,
                    help="fair-share tenant this job bills to "
                         "(ISSUE 14): deficit-round-robin pop order "
                         "and --tenant-weight quotas group by it")
    sp.add_argument("--devices", type=int, default=1)
    sp.add_argument("--devices-min", type=int, default=None,
                    help="elastic floor (sharded): the scheduler may "
                         "shrink the mesh to this")
    sp.add_argument("--devices-max", type=int, default=None,
                    help="elastic ceiling (sharded): grow bound")
    sp.add_argument("--maxstates", type=int, default=None)
    sp.add_argument("--maxseconds", type=float, default=None)
    sp.add_argument("--pipeline", type=int, default=None)
    sp.add_argument("--inject", default=None,
                    help="deterministic fault plan for this job "
                         "(tpuvsr/resilience/faults.py grammar)")
    sp.add_argument("--sim", action="store_true",
                    help="submit a kind=\"sim\" job: a walker-fleet "
                         "defect hunt (tpuvsr/sim) instead of a BFS "
                         "check")
    sp.add_argument("--walkers", type=int, default=None,
                    help="sim jobs: fleet size (default 512)")
    sp.add_argument("--depth", type=int, default=None,
                    help="sim jobs: walk depth bound (default 100)")
    sp.add_argument("--num", type=int, default=None,
                    help="sim jobs: stop after N walks (default "
                         "10000; --hunt for the continuous mode)")
    sp.add_argument("--seed", type=int, default=None,
                    help="sim jobs: fleet RNG seed (walk i replays "
                         "identically for any walker count/mesh)")
    sp.add_argument("--split", action="store_true",
                    help="sim jobs: importance splitting (fingerprint-"
                         "novelty kill/clone at chunk boundaries)")
    sp.add_argument("--hunt", action="store_true",
                    help="sim jobs: continuous hunt — run until "
                         "cancelled/preempted, collecting deduped "
                         "violations")
    sp.add_argument("--validate", default=None,
                    metavar="TRACES.jsonl",
                    help="submit a kind=\"validate\" job: check every "
                         "recorded implementation trace in the file "
                         "against the spec (tpuvsr/validate) instead "
                         "of a BFS check")
    sp.add_argument("--batch", type=int, default=None,
                    help="validate jobs: traces per round (default "
                         "1024)")
    sp.add_argument("--batch-per-device", type=int, default=None,
                    help="validate jobs: tie the round size to the "
                         "device allocation (elastic trace-batch "
                         "placement: batch = N * devices, rescaled "
                         "when the scheduler reshapes the job)")
    sp.add_argument("--interp", action="store_true",
                    help="validate jobs: use the interpreter "
                         "reference validator — a LIGHT job the "
                         "worker's multi-runner threads handle with "
                         "zero devices (ISSUE 14)")
    sp.add_argument("--lint-only", action="store_true",
                    help="check jobs: speclint report only, no "
                         "engine run — a LIGHT job (zero devices, "
                         "multi-runner lane)")
    sp.add_argument("--stub", action="store_true",
                    help="run the inline counter spec on the stub "
                         "kernel (tier-1 smoke path, no reference "
                         "mount)")
    sp.add_argument("--flag", action="append", default=[],
                    metavar="KEY=VALUE",
                    help="extra job flag (repeatable; JSON values)")
    sp.add_argument("--spool", default=None)
    sp.add_argument("--spool-driver", default=None,
                    choices=("fs", "objstore", "quorum"),
                    help="spool driver for a NEW spool (ISSUE 20); "
                         "an existing spool's persisted choice always "
                         "wins, absent config means fs")
    sp.add_argument("--spool-replicas", type=int, default=None,
                    metavar="N",
                    help="quorum driver replica count (default 3)")
    sp.add_argument("--json", action="store_true")

    sv = sub.add_parser("serve", help="run the dispatch worker(s)")
    sv.add_argument("--spool", default=None)
    sv.add_argument("--spool-driver", default=None,
                    choices=("fs", "objstore", "quorum"),
                    help="spool driver for a NEW spool (ISSUE 20): "
                         "fs (single filesystem, the default), "
                         "objstore (CAS-record claims + epoch "
                         "fencing), quorum (replicated log over N "
                         "directories); an existing spool's persisted "
                         "choice always wins")
    sv.add_argument("--spool-replicas", type=int, default=None,
                    metavar="N",
                    help="quorum driver replica count (default 3)")
    sv.add_argument("--host-lease-timeout", type=float, default=None,
                    help="seconds after which a host whose lease "
                         "record went silent is dead and ALL its "
                         "claims are swept at once (default: the "
                         "heartbeat timeout)")
    sv.add_argument("--drain", action="store_true",
                    help="exit when nothing is claimable")
    sv.add_argument("--devices", type=int, default=None,
                    help="device pool size (default: every visible "
                         "device); with --workers N each worker owns "
                         "a devices/N group")
    sv.add_argument("--workers", type=int, default=1,
                    help="worker processes over the shared spool "
                         "(ISSUE 14): 1 = drain in-process (the "
                         "original mode), N>1 = spawn N serve "
                         "subprocesses and supervise them, 0 = no "
                         "workers (HTTP front only)")
    sv.add_argument("--worker-id", default=None,
                    help="this worker's identity in claim files and "
                         "journals (default: worker-<pid>)")
    sv.add_argument("--max-restarts", type=int, default=3,
                    help="--workers N>1: how many times the parent "
                         "respawns one dead (nonzero-exit) worker "
                         "slot, with exponential backoff; journaled "
                         "as worker_respawn in <spool>/pool.jsonl "
                         "(0 = sweep stale claims only, the pre-"
                         "ISSUE-15 behavior)")
    sv.add_argument("--http", type=int, default=None, metavar="PORT",
                    help="raise the HTTP front on PORT (0 = an "
                         "ephemeral port, printed on stderr): "
                         "submit/status/cancel/list + streamed "
                         "journal tails over the wire "
                         "(tpuvsr/serve/http.py)")
    sv.add_argument("--tenant-weight", action="append", default=[],
                    metavar="TENANT=W",
                    help="fair-share weight for a tenant "
                         "(repeatable; default 1.0 each): a weight-2 "
                         "tenant gets two pops per deficit-round-"
                         "robin round where a weight-1 tenant gets "
                         "one")
    sv.add_argument("--age-every", type=float, default=60.0,
                    help="priority-aging rate: +1 effective priority "
                         "per this many seconds waited (0 disables; "
                         "bounds every job's wait at age_every * "
                         "(top_priority - its_priority + 1))")
    sv.add_argument("--light-threads", type=int, default=2,
                    help="multi-runner threads for light jobs "
                         "(shell / interp-validate / lint-only; 0 "
                         "disables the side lane)")
    sv.add_argument("--heartbeat-timeout", type=float, default=None,
                    help="seconds after which a cross-host claim "
                         "with no heartbeat is recoverable "
                         "(default 300)")
    sv.add_argument("--max-jobs", type=int, default=None)
    sv.add_argument("--max-seconds", type=float, default=None)
    sv.add_argument("--tpu-devices", type=int, default=None,
                    help="reachable TPU devices for the cpu-vs-tpu "
                         "placement advisory (default: "
                         "TPUVSR_TPU_DEVICES env, else the TPU_UP "
                         "flag file scripts/tpu_watch.py maintains, "
                         "else 0)")
    sv.add_argument("--bench-dir", default=None,
                    help="directory of BENCH_r*.json docs for the "
                         "cross-backend throughput advisory "
                         "(default: the repo root)")
    sv.add_argument("--quiet", action="store_true")
    # front-door hardening (ISSUE 18) — see serve/guard.py: auth is
    # on whenever <spool>/tokens.json (or --auth-tokens) exists; the
    # limiter / backpressure / breaker knobs are opt-in
    sv.add_argument("--tls-cert", default=None, metavar="PEM",
                    help="serve the HTTP front over TLS with this "
                         "certificate chain")
    sv.add_argument("--tls-key", default=None, metavar="PEM",
                    help="private key for --tls-cert (omit when the "
                         "key is in the cert file)")
    sv.add_argument("--auth-tokens", default=None, metavar="JSON",
                    help="per-tenant bearer tokens file (default "
                         "<spool>/tokens.json; absent = open mode)")
    sv.add_argument("--rate", type=float, default=None,
                    metavar="PER_S",
                    help="per-tenant token-bucket refill "
                         "(submissions/second; denials are 429 with "
                         "Retry-After)")
    sv.add_argument("--burst", type=float, default=None,
                    help="token-bucket capacity (default: --rate)")
    sv.add_argument("--max-inflight", type=int, default=None,
                    metavar="N",
                    help="per-tenant cap on unfinished jobs (429 "
                         "past it)")
    sv.add_argument("--high-water", type=int, default=None,
                    metavar="N",
                    help="queue-depth backpressure: 503 new "
                         "submissions while the backlog exceeds N")
    sv.add_argument("--max-body", type=int, default=None,
                    metavar="BYTES",
                    help="request body cap (413 past it; default "
                         "1 MiB)")
    sv.add_argument("--breaker-threshold", type=int, default=3,
                    metavar="K",
                    help="circuit breaker: trip a (tenant, spec) "
                         "after K failures in --breaker-window "
                         "seconds (fail-fast 'breaker-open')")
    sv.add_argument("--breaker-window", type=float, default=60.0)
    sv.add_argument("--breaker-cooldown", type=float, default=2.0,
                    help="seconds before a tripped breaker half-opens "
                         "for one probe (doubles per re-trip)")

    st = sub.add_parser("status", help="queue / per-job status")
    st.add_argument("job_id", nargs="?", default=None)
    st.add_argument("--spool", default=None)
    st.add_argument("--json", action="store_true")
    st.add_argument("--tail", type=int, default=0, metavar="N",
                    help="with a JOB: print the last N journal events")

    ca = sub.add_parser("cancel", help="cancel a job")
    ca.add_argument("job_id")
    ca.add_argument("--spool", default=None)
    ca.add_argument("--json", action="store_true")

    te = sub.add_parser("telemetry",
                        help="fold the spool's journals into the "
                             "fleet telemetry view (ISSUE 17)")
    te.add_argument("spool_pos", nargs="?", default=None,
                    metavar="SPOOL",
                    help="spool directory (also --spool / "
                         "TPUVSR_SPOOL)")
    te.add_argument("--spool", default=None)
    te.add_argument("--watch", action="store_true",
                    help="repoll and redraw every --interval seconds "
                         "until interrupted")
    te.add_argument("--interval", type=float, default=2.0)
    te.add_argument("--json", action="store_true",
                    help="print the tpuvsr-telemetry/1 snapshot "
                         "document")
    te.add_argument("--prom", action="store_true",
                    help="print the Prometheus text exposition "
                         "(format 0.0.4), as GET /v1/metrics serves")
    te.add_argument("--window", type=float, default=10.0,
                    help="fold window seconds (default 10)")
    te.add_argument("--slo-queue-wait", type=float, default=None,
                    metavar="SECONDS",
                    help="SLO watchdog: journal slo_breach when any "
                         "tenant's p99 queue wait exceeds this")
    te.add_argument("--no-breach-journal", action="store_true",
                    help="fold only — never append slo_breach events "
                         "or publish baselines (pure read)")
    return p


def _queue(args):
    return JobQueue(args.spool or default_spool(),
                    driver=getattr(args, "spool_driver", None),
                    replicas=getattr(args, "spool_replicas", None))


def cmd_submit(args):
    if not args.spec and not args.stub:
        print("submit: a SPEC path (or --stub) is required",
              file=sys.stderr)
        return EX_USAGE
    try:
        flags = _flag_pairs(args.flag)
    except ValueError as e:
        print(f"submit: {e}", file=sys.stderr)
        return EX_USAGE
    q = _queue(args)
    for k in ("maxstates", "maxseconds", "pipeline", "inject",
              "walkers", "depth", "num", "seed"):
        v = getattr(args, k)
        if v is not None:
            flags[k] = v
    if args.stub:
        flags["stub"] = True
    if args.split:
        flags["split"] = True
    if args.hunt:
        flags["hunt"] = True
    if args.validate and args.sim:
        print("submit: --validate and --sim are different job kinds "
              "(a trace-validation batch vs a walker-fleet hunt); "
              "pick one", file=sys.stderr)
        return EX_USAGE
    if args.interp and not args.validate:
        print("submit: --interp selects the interpreter validator; "
              "it needs --validate", file=sys.stderr)
        return EX_USAGE
    if args.lint_only and (args.sim or args.validate):
        print("submit: --lint-only is a check-job mode (speclint "
              "report, no engine run); it conflicts with "
              "--sim/--validate", file=sys.stderr)
        return EX_USAGE
    if args.lint_only:
        flags["lint_only"] = True
    if args.validate:
        if args.interp:
            flags["interp"] = True
        if args.maxstates is not None:
            # mirrors the CLI's -maxstates/-validate exit-2 contract:
            # the worker would silently ignore it otherwise
            print("submit: --maxstates bounds BFS; a validate job is "
                  "bounded by its trace file and --maxseconds",
                  file=sys.stderr)
            return EX_USAGE
        flags["traces"] = args.validate
        if args.batch is not None:
            flags["batch"] = args.batch
        if args.batch_per_device is not None:
            flags["batch_per_device"] = args.batch_per_device
    elif args.batch is not None or args.batch_per_device is not None:
        print("submit: --batch/--batch-per-device size a validate "
              "job's trace rounds; they need --validate",
              file=sys.stderr)
        return EX_USAGE
    kind = ("validate" if args.validate
            else "sim" if args.sim else "check")
    if not args.sim and (args.split or args.hunt
                         or args.walkers is not None
                         or args.depth is not None
                         or args.num is not None
                         or args.seed is not None):
        print("submit: --walkers/--depth/--num/--seed/--split/--hunt "
              "need --sim (they describe a walker-fleet job; check "
              "jobs take --maxstates/--maxseconds)", file=sys.stderr)
        return EX_USAGE
    job = q.submit(args.spec or "<stub:ObsCounter>",
                   cfg=args.config, engine=args.engine, kind=kind,
                   flags=flags, tenant=args.tenant,
                   priority=args.priority, devices=args.devices,
                   devices_min=args.devices_min,
                   devices_max=args.devices_max)
    if args.json:
        print(json.dumps(job.to_dict(), default=str))
    else:
        print(f"submitted {job.job_id} ({job.spec}, engine "
              f"{job.engine}, priority {job.priority}"
              + (f", tenant {job.tenant}" if job.tenant else "")
              + ")")
    return 0


def _fold_progress(journal_path, out, fold, nonempty):
    """The shared journal fold behind the per-kind progress rows:
    line-by-line JSON parse tolerating torn tails, ``fold(event, ev,
    out)`` per parsed event, ``out`` returned only when ``nonempty``
    says the journal actually carried that kind's progress (None
    otherwise, like an unreadable file — the caller omits the row)."""
    try:
        with open(journal_path) as f:
            for line in f:
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue
                fold(ev.get("event"), ev, out)
    except OSError:
        return None
    return out if nonempty(out) else None


def _sim_progress(journal_path):
    """Sim-specific per-job progress folded from the journal: the
    latest chunk's walks/steps/depth, best novelty, and the unique
    violation count — the fleet's analog of the BFS level rows
    (ISSUE 7 satellite)."""
    def fold(e, ev, out):
        if e == "sim_chunk":
            out["walks"] = ev.get("walks", out["walks"])
            out["steps"] = ev.get("steps", out["steps"])
            out["depth"] = ev.get("depth", out["depth"])
        elif e == "split" and ev.get("novelty_best") is not None:
            out["novelty_best"] = ev["novelty_best"]
        elif e == "hunt_violation":
            out["unique_violations"] += 1
        elif e == "hunt_elastic":
            out["walkers"] = ev.get("to", out["walkers"])

    return _fold_progress(
        journal_path,
        {"walks": 0, "steps": 0, "depth": 0, "novelty_best": None,
         "unique_violations": 0, "walkers": None}, fold,
        lambda o: (o["walks"] or o["steps"]
                   or o["unique_violations"]))


def _validate_progress(journal_path):
    """Validate-specific per-job progress folded from the journal:
    cumulative traces checked / divergences from the latest
    ``validate_chunk``, plus the first divergence's location — the
    trace-validation analog of the sim rows (ISSUE 8)."""
    def fold(e, ev, out):
        if e == "validate_chunk":
            out["traces"] = ev.get("traces", out["traces"])
            out["divergences"] = ev.get("divergences",
                                        out["divergences"])
            out["step"] = ev.get("depth", out["step"])
        elif e == "run_end" and ev.get("traces") is not None:
            # chunk rows are mid-round progress; the run summary has
            # the final totals
            out["traces"] = ev["traces"]
            out["divergences"] = ev.get("divergences",
                                        out["divergences"])
        elif e == "divergence" and out["first_divergence"] is None:
            out["first_divergence"] = {"trace": ev.get("trace"),
                                       "step": ev.get("step")}

    return _fold_progress(
        journal_path,
        {"traces": 0, "divergences": 0, "step": 0,
         "first_divergence": None}, fold,
        lambda o: o["traces"] or o["divergences"])


def job_doc(q, job, tail=0):
    """One job's status document — THE job record both query surfaces
    serve verbatim: the ``status`` verb prints it and the HTTP front's
    ``GET /v1/jobs/<id>`` returns it (ISSUE 14: the CLI is one client
    among many, so the document is built once, here).  ``exit_code``
    is the unified table's code for the job's state
    (``tpuvsr/exitcodes.py``; None while non-terminal)."""
    from ..exitcodes import state_exit
    doc = job.to_dict()
    doc["exit_code"] = state_exit(job.state)
    jp = q.journal_path(job.job_id)
    mp = q.metrics_path(job.job_id)
    doc["journal"] = jp if os.path.exists(jp) else None
    doc["metrics"] = mp if os.path.exists(mp) else None
    if job.kind == "sim" and os.path.exists(jp):
        doc["sim"] = _sim_progress(jp)
    if job.kind == "validate" and os.path.exists(jp):
        doc["validate"] = _validate_progress(jp)
    tail = max(0, int(tail or 0))    # a negative tail must not turn
    #                                  into "everything but the head"
    if tail and os.path.exists(jp):
        rows = []
        with open(jp) as f:
            for line in f.readlines()[-tail:]:
                try:
                    rows.append(json.loads(line))
                except ValueError:
                    pass
        doc["journal_tail"] = rows
    return doc


def cmd_status(args):
    q = _queue(args)
    if args.job_id:
        try:
            job = q.get(args.job_id)
        except QueueError as e:
            print(f"status: {e}", file=sys.stderr)
            return EX_USAGE
        doc = job_doc(q, job, tail=args.tail)
        tail = doc.get("journal_tail", [])
        if args.json:
            print(json.dumps(doc, default=str))
        else:
            for k in ("job_id", "state", "exit_code", "kind", "tenant",
                      "spec", "engine", "priority", "devices",
                      "attempts", "reason"):
                print(f"{k}: {doc.get(k)}")
            if doc.get("rescue"):
                print(f"rescue: {doc['rescue']}")
            if doc.get("sim"):
                s = doc["sim"]
                print(f"sim: {s['walks']} walks, {s['steps']} steps, "
                      f"depth {s['depth']}, "
                      f"{s['unique_violations']} unique violation(s)"
                      + (f", best novelty {s['novelty_best']}"
                         if s["novelty_best"] is not None else ""))
            if doc.get("validate"):
                v = doc["validate"]
                fd = v.get("first_divergence")
                print(f"validate: {v['traces']} trace(s) checked, "
                      f"{v['divergences']} divergence(s)"
                      + (f", first at trace {fd['trace']} event "
                         f"{fd['step']}" if fd else ""))
            if doc.get("result"):
                r = {k: v for k, v in doc["result"].items()
                     if k not in ("trace", "violations")}
                if doc["result"].get("violations") is not None:
                    r["violations"] = len(doc["result"]["violations"])
                print(f"result: {json.dumps(r, default=str)}")
            for ev in tail:
                print(f"  {ev.get('event')}: "
                      + ", ".join(f"{k}={v}" for k, v in ev.items()
                                  if k not in ("event", "ts",
                                               "run_id")))
        return 0
    jobs = [j.to_dict() for j in q.jobs()]
    from ..serve.fairshare import TenantLedger
    tenants = TenantLedger.fold(q.jobs())
    if args.json:
        # the queue fold plus the fleet telemetry fold in one doc
        # (ISSUE 17): dashboards scraping `status --json` get the
        # same tpuvsr-telemetry/1 snapshot /v1/telemetry serves
        from ..obs.telemetry import TelemetryAggregator
        agg = TelemetryAggregator(q.spool, journal_breaches=False)
        agg.poll()
        print(json.dumps({"stats": q.stats(), "jobs": jobs,
                          "tenants": tenants,
                          "spool": q.spool_status(),
                          "telemetry": agg.snapshot()}, default=str))
    else:
        st = q.stats()
        print("queue: " + ", ".join(f"{k}={v}" for k, v in st.items()
                                    if v and k != "total")
              + f" (total {st['total']})")
        sp = q.spool_status()
        if sp["driver"] != "fs" or sp["replicas"]:
            reps = sp["replicas"]
            print(f"  spool: driver={sp['driver']}"
                  + (f" replicas={reps['live']}/{reps['total']} live"
                     + (f" (lost: {reps['lost']})" if reps["lost"]
                        else "") if reps else ""))
        for j in jobs:
            print(f"  {j['job_id']:>18} {j['state']:>20} "
                  f"prio={j['priority']} dev={j['devices']} "
                  f"attempts={j['attempts']} "
                  f"tenant={j.get('tenant') or '-'} {j['spec']}")
        if len(tenants) > 1 or "-" not in tenants:
            for t, row in sorted(tenants.items()):
                print(f"  tenant {t}: {row['jobs']} job(s), "
                      f"{row['queued']} queued, {row['active']} "
                      f"active, {row['done']} done, "
                      f"{row['service_s']}s served")
    return 0


def cmd_cancel(args):
    q = _queue(args)
    try:
        job = q.cancel(args.job_id)
    except QueueError as e:
        print(f"cancel: {e}", file=sys.stderr)
        return EX_USAGE
    note = ("cancel requested (running job rescues at the next level "
            "boundary)" if job.state == "running" else "cancelled")
    if args.json:
        print(json.dumps({"job_id": job.job_id, "state": job.state,
                          "note": note}))
    else:
        print(f"{job.job_id}: {note}")
    return 0


def cmd_telemetry(args):
    """``tpuvsr telemetry [SPOOL] [--watch] [--json | --prom]`` — the
    CLI face of the fleet telemetry fold.  Imports neither jax nor the
    engines (the aggregator is pure stdlib), so it is milliseconds
    against a live spool and safe to leave running beside a serve."""
    from ..obs.telemetry import (TelemetryAggregator, prometheus_text,
                                 render_watch)
    spool = args.spool_pos or args.spool or default_spool()
    if not os.path.isdir(spool):
        print(f"telemetry: no spool at {spool!r}", file=sys.stderr)
        return EX_USAGE
    slo = {}
    if args.slo_queue_wait is not None:
        slo["queue_wait_p99_s"] = args.slo_queue_wait
    agg = TelemetryAggregator(
        spool, window_s=args.window, slo=slo,
        journal_breaches=not args.no_breach_journal)

    def emit():
        agg.poll()
        snap = agg.snapshot()
        if args.prom:
            print(prometheus_text(snap), end="")
        elif args.json:
            print(json.dumps(snap, default=str))
        else:
            print(render_watch(snap))

    if not args.watch:
        emit()
        return 0
    try:
        while True:
            emit()
            print("---", flush=True)
            time.sleep(max(0.1, args.interval))
    except KeyboardInterrupt:
        pass
    return 0


def _policy_from_args(args):
    from ..serve.fairshare import FairSharePolicy
    try:
        weights = _flag_pairs(args.tenant_weight)
    except ValueError as e:
        raise ValueError(f"--tenant-weight wants TENANT=WEIGHT: {e}")
    return FairSharePolicy(weights=weights, age_every=args.age_every)


def _guard_from_args(args, spool):
    """The serve verb's admission guard (ISSUE 18).  Always built —
    a default Guard still enforces the body cap and honours a
    spool-local tokens.json — with the limiter / backpressure /
    breaker knobs layered on from the flags."""
    from ..serve.guard import Guard
    kw = {}
    if args.auth_tokens is not None:
        kw["tokens_path"] = args.auth_tokens
    if args.max_body is not None:
        kw["max_body"] = args.max_body
    return Guard(
        spool, rate=args.rate, burst=args.burst,
        max_inflight=args.max_inflight, high_water=args.high_water,
        breaker_k=args.breaker_threshold,
        breaker_window=args.breaker_window,
        breaker_cooldown=args.breaker_cooldown, **kw)


def _serve_pool(args, q, log, t0, http):
    """``serve --workers N`` (N > 1): spawn N worker subprocesses
    over the spool and stay a thin supervisor — sweep stale claims on
    a cadence (a SIGKILLed child's jobs requeue onto the survivors)
    and host the optional HTTP front."""
    from ..serve.pool import WorkerPool
    passthrough = ["--age-every", str(args.age_every),
                   "--light-threads", str(args.light_threads)]
    for tw in args.tenant_weight:
        passthrough += ["--tenant-weight", tw]
    if args.heartbeat_timeout is not None:
        passthrough += ["--heartbeat-timeout",
                        str(args.heartbeat_timeout)]
    # the placement advisory flags must reach the children too — a
    # child falling back to auto-detection would contradict an
    # explicit --tpu-devices/--bench-dir on the parent
    if args.tpu_devices is not None:
        passthrough += ["--tpu-devices", str(args.tpu_devices)]
    if args.bench_dir is not None:
        passthrough += ["--bench-dir", args.bench_dir]
    # the breaker runs IN the workers (it guards device time): each
    # child builds its own guard from the same thresholds
    passthrough += ["--breaker-threshold", str(args.breaker_threshold),
                    "--breaker-window", str(args.breaker_window),
                    "--breaker-cooldown", str(args.breaker_cooldown)]
    if args.quiet:
        passthrough.append("--quiet")
    pool = WorkerPool(
        q.spool, args.workers, devices=args.devices,
        drain=args.drain, max_seconds=args.max_seconds,
        max_jobs=args.max_jobs, extra_args=passthrough, log=log,
        max_restarts=args.max_restarts)
    pool.start()
    while True:
        # respawn BEFORE the liveness check: a tick where every child
        # died nonzero must relaunch, not drain the pool (ISSUE 15
        # satellite — the ROADMAP item 2 respawn residual).  A slot
        # waiting out its backoff counts as pending, not drained
        pool.respawn_dead()
        if not pool.alive() and not pool.pending_respawn():
            break
        # the pool parent IS this host's lease writer (ISSUE 20):
        # every sweep tick renews the lease a SURVIVOR host judges us
        # by — if we go silent past --host-lease-timeout, all of our
        # workers' claims are swept in one pass
        q.host_heartbeat()
        q.recover_stale(log=log)
        time.sleep(0.5)
    codes = pool.wait()
    q.recover_stale(log=log)
    q.refresh()
    print(json.dumps({"workers": args.workers, "worker_rcs": codes,
                      "stats": q.stats(),
                      "http": http.address if http else None,
                      "elapsed_s": round(time.time() - t0, 3)}))
    return 0 if all(c == 0 for c in codes) else 70


def cmd_serve(args):
    q = JobQueue(args.spool or default_spool(),
                 driver=args.spool_driver,
                 replicas=args.spool_replicas,
                 host_lease_timeout=args.host_lease_timeout,
                 **({"heartbeat_timeout": args.heartbeat_timeout}
                    if args.heartbeat_timeout is not None else {}))
    log = (None if args.quiet
           else lambda m: print(f"[tpuvsr] {m}", file=sys.stderr))
    t0 = time.time()
    guard = _guard_from_args(args, q.spool)
    http = None
    if args.http is not None:
        from ..serve.http import ServiceHTTP
        http = ServiceHTTP(q.spool, port=args.http, log=log,
                           guard=guard, tls_cert=args.tls_cert,
                           tls_key=args.tls_key).start()
        print(f"[tpuvsr] http front: {http.address}", file=sys.stderr)
    try:
        if args.workers == 0:
            # front-only mode: no drain loop, submissions land on the
            # spool for workers elsewhere
            if http is None:
                print("serve: --workers 0 without --http serves "
                      "nothing", file=sys.stderr)
                return EX_USAGE
            end = (None if args.max_seconds is None
                   else t0 + args.max_seconds)
            try:
                while end is None or time.time() < end:
                    time.sleep(0.2)
            except KeyboardInterrupt:
                pass
            q.refresh()     # fold submissions the front's own queue
            #                 instance appended while we slept
            print(json.dumps({"workers": 0, "http": http.address,
                              "stats": q.stats(),
                              "elapsed_s": round(time.time() - t0,
                                                 3)}))
            return 0
        try:
            policy = _policy_from_args(args)
        except ValueError as e:
            print(f"serve: {e}", file=sys.stderr)
            return EX_USAGE
        if args.workers > 1:
            return _serve_pool(args, q, log, t0, http)
        from .worker import Worker
        tpu = args.tpu_devices
        if tpu is None:
            from .scheduler import detect_tpu_devices
            tpu = detect_tpu_devices()
        devices = args.devices
        if devices is None:
            # a pool child with a pinned device group (ISSUE 18):
            # its DevicePool budget IS the slice size — never count
            # the whole host's devices from inside a pinned slot
            group = os.environ.get("TPUVSR_DEVICE_GROUP")
            if group and ":" in group:
                try:
                    devices = max(1, int(group.split(":")[1]))
                except ValueError:
                    pass
        w = Worker(q, devices=devices, log=log,
                   tpu_devices=tpu, bench_dir=args.bench_dir,
                   owner=args.worker_id, policy=policy,
                   light_threads=args.light_threads, guard=guard)
        runs = w.drain(max_jobs=args.max_jobs,
                       max_seconds=args.max_seconds,
                       idle_exit=args.drain)
        stats = q.stats()
        print(json.dumps({"runs": runs, "stats": stats,
                          "processed": w.processed,
                          "http": http.address if http else None,
                          "elapsed_s": round(time.time() - t0, 3)}))
        return 0
    finally:
        if http is not None:
            http.stop()


def main(argv=None):
    parser = build_parser()
    args = parser.parse_args(argv)
    return {"submit": cmd_submit, "status": cmd_status,
            "cancel": cmd_cancel, "serve": cmd_serve,
            "telemetry": cmd_telemetry}[args.verb](args)


if __name__ == "__main__":
    sys.exit(main())
