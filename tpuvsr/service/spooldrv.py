"""Spool drivers: the durable-storage seam under the job queue.

``JobQueue`` (ISSUE 6) was written against one POSIX filesystem —
``O_CREAT|O_EXCL`` claim files, mtime heartbeats, fsync'd JSONL on one
mount.  That story breaks the moment the control plane must survive a
machine: an object store has no atomic-exclusive create and no
trustworthy mtime, a lost NFS mount takes the whole queue down, and a
zombie worker whose claim was recovered on another host can still
append a terminal transition (the split-brain hole mtime heartbeats
only papered over).  This module is ROADMAP item 2(b): one small
driver interface — append-record log, conditional-put claim, explicit
heartbeat record, snapshot-blob get/put, read-from-cursor — with three
implementations:

``fs``
    Today's behavior, extracted verbatim: ``jobs.jsonl`` with
    fsync-per-line appends and torn-tail repair, link-danced claim
    files, mtime heartbeats.  A PR-18-era spool opens under this
    driver with no migration (the driver config file is simply
    absent); the mtime is consulted only as a FALLBACK for claims
    that predate the explicit heartbeat sidecar.

``objstore``
    Claims become versioned compare-and-swap records in a ``claims``
    record stream — a claim carries an **epoch** (the job's attempt
    number), heartbeats are appended records (no mtime anywhere), and
    every terminal-state append is **fenced** on the claim epoch: a
    zombie worker whose claim was recovered can never commit
    (:class:`FencedError`, journaled as a ``fence`` event).  The CAS
    sections run under one advisory file lock, standing in for the
    conditional-put primitive every real object store provides
    (If-Match / generation preconditions).

``quorum``
    A tiny replicated log over N directories standing in for N
    hosts/disks.  Appends are framed ``{seq, crc, rec}`` lines written
    to every live replica and acknowledged at a write quorum
    ``W = floor(N/2) + 1``; reads merge replicas by (seq, CRC),
    holding back torn tails PER REPLICA; losing one replica leaves
    the full service running (``replica_lost`` journaled), and a
    rejoining replica catches up via anti-entropy
    (:meth:`QuorumDriver.maintain`, ``replica_rejoin`` journaled).
    Claims/fencing ride the same CAS-record machinery as ``objstore``
    — over the replicated stream.

Driver selection persists in ``<spool>/spooldrv.json`` (absent means
``fs``, which is how legacy spools keep working).  Every driver also
carries **host leases** — a ``hosts`` record stream the pool parents
heartbeat through — so a survivor host's ``recover_stale`` can sweep
an entire dead host's claims at once instead of waiting out each
claim's own heartbeat window (the host-death-failover drill in
``scripts/fault_matrix.py``).

Driver-plane events (``replica_lost`` / ``replica_rejoin`` /
``fence`` / ``host_lease``) are journaled to ``<spool>/spool.jsonl``
(run_id ``spool``) and folded by the PR 17 telemetry plane onto
``/v1/metrics``.

Deliberately jax-free, like the queue: submit/status stay
milliseconds.
"""

from __future__ import annotations

import contextlib
import json
import os
import socket
import threading
import time
import zlib

#: the driver-selection config file inside a spool directory; absent
#: means the ``fs`` driver (every pre-driver spool keeps working)
CONFIG_NAME = "spooldrv.json"

DRIVERS = ("fs", "objstore", "quorum")

#: default replica count for the quorum driver
DEFAULT_REPLICAS = 3


def current_host():
    """This process's host identity for claims and leases.
    ``TPUVSR_HOST`` overrides the real hostname so fault drills can
    fake a multi-host fleet on one box (two pools, two 'hosts', one
    spool)."""
    return os.environ.get("TPUVSR_HOST") or socket.gethostname()


class SpoolError(RuntimeError):
    """A driver-level failure (write quorum lost, config mismatch)."""


class FencedError(SpoolError):
    """A fenced append was rejected: the appender's claim epoch is no
    longer the live claim — its claim was recovered (and possibly
    re-issued) while it was presumed dead.  The zombie must NOT
    commit; the rejection is journaled as a ``fence`` event."""


def _fsync_append(path, rec):
    """Append one JSON line durably (the record-stream write
    primitive, shared by every driver).

    Repairs a torn tail first: a writer killed mid-append leaves a
    partial line with no trailing newline, and appending straight onto
    it would MERGE two records into one garbage line (losing the valid
    one).  Terminating the torn fragment turns it into its own
    invalid, skipped line instead."""
    data = (json.dumps(rec, sort_keys=True, default=str)
            + "\n").encode()
    fd = os.open(path, os.O_CREAT | os.O_WRONLY | os.O_APPEND, 0o644)
    try:
        # torn-tail check via the same fd's file: a crashed writer's
        # partial record is STATIC (every live writer appends with one
        # O_APPEND write syscall, which local filesystems apply
        # atomically — no mid-flight interleaving to race with)
        try:
            with open(path, "rb") as rf:
                rf.seek(0, os.SEEK_END)
                if rf.tell() > 0:
                    rf.seek(-1, os.SEEK_END)
                    if rf.read(1) != b"\n":
                        os.write(fd, b"\n")
        except OSError:
            pass
        # ONE write syscall: concurrent appenders (submit while serve)
        # can never interleave inside each other's records
        os.write(fd, data)
        os.fsync(fd)
    finally:
        os.close(fd)


def _read_new_lines(path, pos):
    """``(complete_lines, new_pos)`` of one record file since byte
    ``pos`` — a torn final line (a writer killed mid-append, or one we
    raced) is held back until it is completed.  The one tailing
    discipline every stream reader shares."""
    out = []
    try:
        size = os.path.getsize(path)
    except OSError:
        return out, pos
    if size <= pos:
        return out, pos
    with open(path) as f:
        f.seek(pos)
        while True:
            line = f.readline()
            if not line or not line.endswith("\n"):
                break            # torn tail: re-read next refresh
            pos = f.tell()
            line = line.strip()
            if line:
                out.append(line)
    return out, pos


def _rec_crc(rec):
    """CRC32 of a record's canonical JSON — what the quorum frames
    carry so a merge-read can reject a bit-rotted replica copy."""
    return zlib.crc32(json.dumps(rec, sort_keys=True,
                                 default=str).encode()) & 0xFFFFFFFF


def _atomic_write(path, data):
    tmp = f"{path}.{os.getpid()}.{threading.get_ident()}.tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def open_driver(spool, driver=None, replicas=None):
    """Open (or create) the spool's driver.

    The persisted choice in ``<spool>/spooldrv.json`` wins; asking for
    a DIFFERENT driver on an existing configured spool is an error
    (the records are not interchangeable).  A spool with no config is
    an ``fs`` spool — exactly how every pre-driver spool opens with no
    migration — and explicit non-``fs`` choices write the config on
    first open so every later opener (workers, submit, status,
    telemetry) auto-detects."""
    spool = os.path.abspath(spool)
    cfg_path = os.path.join(spool, CONFIG_NAME)
    existing = None
    try:
        with open(cfg_path) as f:
            existing = json.load(f)
    except (OSError, ValueError):
        existing = None
    if existing:
        cfg_driver = existing.get("driver", "fs")
        if driver is not None and driver != cfg_driver:
            raise SpoolError(
                f"spool {spool} is a {cfg_driver!r} spool; cannot "
                f"open it with driver {driver!r}")
        driver = cfg_driver
        if replicas is None:
            replicas = existing.get("replicas")
    if driver is None:
        driver = "fs"            # legacy / default: no config written
    if driver not in DRIVERS:
        raise SpoolError(f"unknown spool driver {driver!r} "
                         f"(want one of {DRIVERS})")
    if existing is None and driver != "fs":
        os.makedirs(spool, exist_ok=True)
        _atomic_write(cfg_path, json.dumps(
            {"driver": driver,
             **({"replicas": int(replicas or DEFAULT_REPLICAS)}
                if driver == "quorum" else {})},
            sort_keys=True).encode())
    if driver == "objstore":
        return ObjStoreDriver(spool)
    if driver == "quorum":
        return QuorumDriver(spool,
                            replicas=int(replicas or DEFAULT_REPLICAS))
    return FsDriver(spool)


class SpoolDriver:
    """The driver interface + the pieces every driver shares (cancel
    markers, the driver-event journal, host leases).

    Streams are named append-only record logs (``jobs`` is the queue's
    state log, ``hosts`` the lease stream, ``claims`` the CAS-record
    claim log of the record-claim drivers).  ``read`` takes and
    returns an opaque cursor (pass ``None`` to start from the
    beginning) and NEVER yields a torn record."""

    name = None

    def __init__(self, spool):
        self.spool = os.path.abspath(spool)
        self.claims_dir = os.path.join(self.spool, "claims")
        os.makedirs(self.claims_dir, exist_ok=True)
        self._tlock = threading.RLock()
        self._flock = threading.local()
        self._hosts = {}             # host -> {"ts", "pid"}
        self._hosts_cursor = None
        self._leased = set()         # hosts THIS instance journaled

    @contextlib.contextmanager
    def _spool_lock(self):
        """The spool's cross-process advisory lock (one ``flock`` on
        ``<spool>/.spool.lock``) — what serializes every conditional
        section (CAS claims, fenced appends, quorum seq assignment)
        across processes.  Reentrant PER THREAD via a depth counter:
        a conditional section may call plain ``append`` underneath
        itself without self-deadlocking on a second fd's flock."""
        import fcntl
        depth = getattr(self._flock, "depth", 0)
        if depth == 0:
            fd = os.open(os.path.join(self.spool, ".spool.lock"),
                         os.O_CREAT | os.O_RDWR, 0o644)
            try:
                fcntl.flock(fd, fcntl.LOCK_EX)
            except OSError:
                os.close(fd)
                raise
            self._flock.fd = fd
        self._flock.depth = depth + 1
        try:
            yield
        finally:
            self._flock.depth -= 1
            if self._flock.depth == 0:
                fd = self._flock.fd
                self._flock.fd = None
                fcntl.flock(fd, fcntl.LOCK_UN)
                os.close(fd)

    # -- record streams (driver-specific) -----------------------------
    def append(self, stream, rec):
        raise NotImplementedError

    def read(self, stream, cursor=None):
        """``(records, cursor)`` of every complete record appended
        since ``cursor``."""
        raise NotImplementedError

    def append_fenced(self, stream, rec, *, job_id, epoch):
        """Append ``rec`` only if ``epoch`` is still the live claim
        epoch of ``job_id`` — the zombie fence.  Raises
        :class:`FencedError` (journaling a ``fence`` event) when the
        claim is gone or re-issued at a newer epoch."""
        raise NotImplementedError

    # -- claims (driver-specific) --------------------------------------
    def try_claim(self, job_id, *, owner, epoch):
        """Conditionally create the claim ``(job_id, epoch)`` — the
        exactly-once primitive.  True iff WE created it; False on any
        existing live claim (a lost race, never an error)."""
        raise NotImplementedError

    def claim_info(self, job_id):
        """The live claim's ``{pid, owner, host, epoch, ts}`` or
        ``None``."""
        raise NotImplementedError

    def claim_age(self, job_id):
        """Seconds since the claim's last explicit heartbeat record
        (``None`` when there is no claim).  Freshness decisions route
        through THIS — never through file mtimes — so coarse or
        skewed cross-host timestamps can't fake liveness; only the
        ``fs`` driver ever consults an mtime, and only as a fallback
        for claims written before the heartbeat sidecar existed."""
        raise NotImplementedError

    def heartbeat(self, job_id):
        """Record a liveness heartbeat for a held claim.  False when
        the claim is gone (job finished/requeued under us)."""
        raise NotImplementedError

    def release_claim(self, job_id, *, epoch=None):
        """Drop the claim (and its heartbeat state).  With ``epoch``,
        only a claim AT that epoch is released — a conditional delete,
        so a zombie's release can't drop a successor's claim."""
        raise NotImplementedError

    # -- cancel markers (shared: advisory flags, no atomicity needed) --
    def _cancel_path(self, job_id):
        return os.path.join(self.claims_dir, f"{job_id}.cancel")

    def set_cancel(self, job_id):
        with open(self._cancel_path(job_id), "w") as f:
            f.write(json.dumps({"ts": round(time.time(), 3)}))

    def cancel_requested(self, job_id):
        return os.path.exists(self._cancel_path(job_id))

    def clear_cancel(self, job_id):
        try:
            os.unlink(self._cancel_path(job_id))
        except FileNotFoundError:
            pass

    # -- snapshot blobs ------------------------------------------------
    def _blob_dirs(self):
        return [os.path.join(self.spool, "blobs")]

    def put_blob(self, name, data):
        """Store an opaque snapshot blob under ``name`` (replicated
        by the quorum driver)."""
        for d in self._blob_dirs():
            os.makedirs(d, exist_ok=True)
            _atomic_write(os.path.join(d, name), data)
            _atomic_write(os.path.join(d, name + ".crc"),
                          str(zlib.crc32(data) & 0xFFFFFFFF).encode())

    def get_blob(self, name):
        """The blob bytes, from the first replica whose CRC checks
        out; ``None`` when absent everywhere."""
        for d in self._blob_dirs():
            p = os.path.join(d, name)
            try:
                with open(p, "rb") as f:
                    data = f.read()
                with open(p + ".crc") as f:
                    want = int(f.read().strip())
            except (OSError, ValueError):
                continue
            if (zlib.crc32(data) & 0xFFFFFFFF) == want:
                return data
        return None

    # -- host leases ---------------------------------------------------
    def host_heartbeat(self, host=None, **info):
        """Append one host-lease heartbeat record — what a pool
        parent writes every supervision tick, so a surviving host can
        judge an ENTIRE peer host dead the moment its lease goes stale
        (not one claim at a time).  The first lease a driver instance
        writes for a host is journaled as a ``host_lease`` event."""
        host = host or current_host()
        self.append("hosts", {"host": host, "pid": os.getpid(),
                              "ts": round(time.time(), 3), **info})
        if host not in self._leased:
            self._leased.add(host)
            self._event("host_lease", host=host, pid=os.getpid())

    def hosts(self):
        """The lease fold: ``{host: {"ts", "pid"}}`` with each host's
        LATEST lease record."""
        with self._tlock:
            recs, self._hosts_cursor = self.read("hosts",
                                                 self._hosts_cursor)
            for rec in recs:
                h = rec.get("host")
                if not h:
                    continue
                try:
                    ts = float(rec.get("ts"))
                except (TypeError, ValueError):
                    continue
                cur = self._hosts.get(h)
                if cur is None or ts >= cur["ts"]:
                    self._hosts[h] = {"ts": ts, "pid": rec.get("pid")}
            return dict(self._hosts)

    # -- replica management (quorum only) ------------------------------
    def replica_status(self):
        """``{"total", "live", "lost"}`` for replicated drivers,
        ``None`` for single-store ones."""
        return None

    def maintain(self, log=None):
        """Driver housekeeping (anti-entropy heal, loss detection) —
        called from ``recover_stale`` sweeps.  Returns the list of
        journaled event names."""
        return []

    # -- the driver-event journal --------------------------------------
    @property
    def journal_path(self):
        return os.path.join(self.spool, "spool.jsonl")

    def _event(self, event, **fields):
        from ..obs import Journal
        j = Journal(self.journal_path, run_id="spool",
                    trace_id="", span_id="", parent_span="")
        try:
            j.write(event, **fields)
        finally:
            j.close()


class FsDriver(SpoolDriver):
    """Today's single-filesystem mechanics, extracted verbatim from
    ``JobQueue``: fsync-per-line JSONL streams, link-danced ``O_EXCL``
    claim files, mtime heartbeats — kept bit-for-bit so existing
    spools work unchanged.  New claims additionally record their
    epoch and heartbeat through an explicit ``.hb`` sidecar, so
    freshness decisions stop trusting mtimes except as a legacy
    fallback, and the fence check works here too (best-effort:
    check-then-append, not atomic — the historical fs semantics)."""

    name = "fs"

    def _stream_path(self, stream):
        return os.path.join(self.spool, f"{stream}.jsonl")

    def append(self, stream, rec):
        _fsync_append(self._stream_path(stream), rec)

    def read(self, stream, cursor=None):
        lines, cursor = _read_new_lines(self._stream_path(stream),
                                        cursor or 0)
        out = []
        for line in lines:
            try:
                out.append(json.loads(line))
            except ValueError:
                continue         # an invalid line is skipped, forever
        return out, cursor

    def append_fenced(self, stream, rec, *, job_id, epoch):
        info = self.claim_info(job_id)
        held = None if info is None else info.get("epoch")
        # a claim that predates the driver layer has no epoch — legacy
        # semantics apply (no fence); otherwise the live claim must be
        # OURS at OUR epoch or the append is a zombie's
        if info is None or (held is not None and held != epoch):
            self._event("fence", job_id=job_id, epoch=epoch,
                        holder=held)
            raise FencedError(
                f"job {job_id}: claim epoch {epoch} is stale "
                f"(live claim epoch: {held})")
        self.append(stream, rec)

    # -- claims --------------------------------------------------------
    def _claim_path(self, job_id):
        return os.path.join(self.claims_dir, f"{job_id}.claim")

    def _hb_path(self, job_id):
        return os.path.join(self.claims_dir, f"{job_id}.hb")

    def try_claim(self, job_id, *, owner, epoch):
        path = self._claim_path(job_id)
        # write-then-LINK: the claim file appears fully written or not
        # at all, so a concurrent recover_stale can never read a
        # half-written (pid-less) claim and mistake it for an orphan.
        # The tmp name carries pid AND thread id: two Workers hosted
        # by one process (threads over separate JobQueue instances —
        # their RLocks don't protect each other) must not share a
        # staging file, or the loser's os.link sees it already
        # unlinked (FileNotFoundError, not the race-deciding EEXIST)
        tmp = f"{path}.{os.getpid()}.{threading.get_ident()}.tmp"
        with open(tmp, "w") as f:
            json.dump({"pid": os.getpid(), "owner": owner,
                       "host": current_host(), "epoch": int(epoch),
                       "ts": round(time.time(), 3)}, f)
            f.flush()
            os.fsync(f.fileno())
        try:
            os.link(tmp, path)   # EEXIST decides the race, like O_EXCL
        except FileExistsError:
            return False
        finally:
            os.unlink(tmp)
        self.heartbeat(job_id)
        return True

    def claim_info(self, job_id):
        try:
            with open(self._claim_path(job_id)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def claim_age(self, job_id):
        try:
            with open(self._hb_path(job_id)) as f:
                return time.time() - float(json.load(f)["ts"])
        except (OSError, ValueError, KeyError, TypeError):
            pass
        # legacy fallback: a claim written before the sidecar existed
        # (an old spool, or a test planting raw claim files) is judged
        # by its mtime — the pre-driver behavior, fs-only
        try:
            return time.time() - os.path.getmtime(
                self._claim_path(job_id))
        except OSError:
            return None

    def heartbeat(self, job_id):
        if not os.path.exists(self._claim_path(job_id)):
            return False
        _atomic_write(self._hb_path(job_id), json.dumps(
            {"ts": round(time.time(), 3)}).encode())
        try:
            # keep the mtime fresh too: pre-driver readers (and mixed
            # fleets mid-upgrade) still judge liveness by it
            os.utime(self._claim_path(job_id))
        except OSError:
            return False
        return True

    def release_claim(self, job_id, *, epoch=None):
        if epoch is not None:
            info = self.claim_info(job_id)
            if info is not None and info.get("epoch") is not None \
                    and info["epoch"] != epoch:
                return           # someone else's claim now
        for p in (self._claim_path(job_id), self._hb_path(job_id)):
            try:
                os.unlink(p)
            except FileNotFoundError:
                pass


class _RecordClaimMixin:
    """Claims as CAS records over the driver's own streams — shared by
    ``objstore`` and ``quorum``.  The claim state is a pure fold of
    the ``claims`` record stream (``claim`` / ``hb`` / ``release``
    ops), and every conditional section (claim, fenced append,
    conditional release) runs under the spool's advisory lock — the
    stand-in for a real object store's conditional put."""

    def _claims_init(self):
        self._claims = {}            # job_id -> claim dict
        self._claims_cursor = None

    def _refresh_claims(self):
        with self._tlock:
            recs, self._claims_cursor = self.read(
                "claims", self._claims_cursor)
            for rec in recs:
                op, jid = rec.get("op"), rec.get("job_id")
                if not jid:
                    continue
                if op == "claim":
                    self._claims[jid] = {
                        "pid": rec.get("pid"),
                        "owner": rec.get("owner"),
                        "host": rec.get("host"),
                        "epoch": rec.get("epoch"),
                        "ts": rec.get("ts"),
                        "hb_ts": rec.get("ts")}
                elif op == "hb":
                    cur = self._claims.get(jid)
                    if cur is not None:
                        cur["hb_ts"] = rec.get("ts", cur["hb_ts"])
                elif op == "release":
                    cur = self._claims.get(jid)
                    if cur is not None and (
                            rec.get("epoch") is None
                            or rec["epoch"] == cur["epoch"]):
                        del self._claims[jid]

    def try_claim(self, job_id, *, owner, epoch):
        with self._spool_lock():
            self._refresh_claims()
            if job_id in self._claims:
                return False
            self.append("claims", {
                "op": "claim", "job_id": job_id, "epoch": int(epoch),
                "owner": owner, "pid": os.getpid(),
                "host": current_host(), "ts": round(time.time(), 3)})
            self._refresh_claims()
            return True

    def claim_info(self, job_id):
        self._refresh_claims()
        info = self._claims.get(job_id)
        return dict(info) if info is not None else None

    def claim_age(self, job_id):
        self._refresh_claims()
        info = self._claims.get(job_id)
        if info is None:
            return None
        try:
            return time.time() - float(info["hb_ts"])
        except (TypeError, ValueError, KeyError):
            return None

    def heartbeat(self, job_id):
        self._refresh_claims()
        if job_id not in self._claims:
            return False
        self.append("claims", {"op": "hb", "job_id": job_id,
                               "ts": round(time.time(), 3)})
        return True

    def release_claim(self, job_id, *, epoch=None):
        with self._spool_lock():
            self._refresh_claims()
            cur = self._claims.get(job_id)
            if cur is None:
                return
            if epoch is not None and cur.get("epoch") != epoch:
                return           # conditional delete lost: not ours
            self.append("claims", {
                "op": "release", "job_id": job_id,
                "epoch": cur.get("epoch"),
                "ts": round(time.time(), 3)})
            self._refresh_claims()

    def append_fenced(self, stream, rec, *, job_id, epoch):
        # the whole fence is ONE conditional section: fold the claim
        # stream, check the epoch, append — atomic against every other
        # claim/release/fenced-append in any process
        with self._spool_lock():
            self._refresh_claims()
            cur = self._claims.get(job_id)
            held = None if cur is None else cur.get("epoch")
            if cur is None or held != epoch:
                self._event("fence", job_id=job_id, epoch=epoch,
                            holder=held)
                raise FencedError(
                    f"job {job_id}: claim epoch {epoch} is stale "
                    f"(live claim epoch: {held})")
            self.append(stream, rec)


class ObjStoreDriver(_RecordClaimMixin, SpoolDriver):
    """The object-store shape: nothing but record streams and blobs —
    no exclusive creates, no mtimes.  Stream appends reuse the fs
    fsync-per-line primitive (an object store's append-or-CAS API maps
    onto the same torn-tail-tolerant record log), which also means the
    ``jobs`` stream stays byte-compatible with ``fs`` — only the
    claim/heartbeat/fence plane differs."""

    name = "objstore"

    def __init__(self, spool):
        super().__init__(spool)
        self._claims_init()

    def _stream_path(self, stream):
        return os.path.join(self.spool, f"{stream}.jsonl")

    def append(self, stream, rec):
        _fsync_append(self._stream_path(stream), rec)

    def read(self, stream, cursor=None):
        lines, cursor = _read_new_lines(self._stream_path(stream),
                                        cursor or 0)
        out = []
        for line in lines:
            try:
                out.append(json.loads(line))
            except ValueError:
                continue
        return out, cursor


class QuorumDriver(_RecordClaimMixin, SpoolDriver):
    """A tiny replicated record log over N directories standing in
    for N hosts/disks (see module doc).  Every stream append is
    assigned a global sequence number under the spool lock (the
    stand-in for leader serialization), framed as
    ``{"seq", "crc", "rec"}`` and written+fsynced to every live
    replica; the append succeeds iff at least ``W = floor(N/2) + 1``
    replicas took it.  Reads merge the replicas: any CRC-valid copy of
    a seq serves, torn tails are held back per replica, and the merge
    is deterministic (same replica set + same bytes -> same records).
    Quorum intersection does the durability math: an acked record
    lives on >= W replicas, so after losing any N - W replicas at
    least ``2W - N >= 1`` copy survives."""

    name = "quorum"

    def __init__(self, spool, replicas=DEFAULT_REPLICAS):
        super().__init__(spool)
        self.total = max(1, int(replicas))
        self.write_quorum = self.total // 2 + 1
        self.state_path = os.path.join(self.spool, "replicas.json")
        lost = self._state()
        fresh = not os.path.isdir(os.path.join(self.spool, "replicas"))
        for i in range(self.total):
            # a LOST replica's dir is never recreated here: an empty
            # directory would read as "rejoined" before anti-entropy
            # healed it — rejoin is maintain()'s job, on a dir the
            # operator (or drill) actually brought back
            if i in lost:
                continue
            if fresh or os.path.isdir(self._replica_dir(i)):
                os.makedirs(self._replica_dir(i), exist_ok=True)
            else:
                # a not-lost replica whose dir vanished while no
                # driver was open (a host died and took its store):
                # that is a loss DISCOVERED at open — recreating it
                # empty would count a record-less replica as live
                self._mark_lost(i, lost)
        self._claims_init()

    def _replica_dir(self, i):
        return os.path.join(self.spool, "replicas", f"r{i}")

    def _frame_path(self, i, stream):
        return os.path.join(self._replica_dir(i), f"{stream}.jsonl")

    # -- replica state -------------------------------------------------
    def _state(self):
        try:
            with open(self.state_path) as f:
                doc = json.load(f)
            return set(int(i) for i in doc.get("lost", ()))
        except (OSError, ValueError, TypeError):
            return set()

    def _set_state(self, lost):
        _atomic_write(self.state_path, json.dumps(
            {"total": self.total, "lost": sorted(lost)},
            sort_keys=True).encode())

    def _mark_lost(self, i, lost, log=None):
        lost.add(i)
        self._set_state(lost)
        self._event("replica_lost", replica=i,
                    live=self.total - len(lost), total=self.total)
        if log:
            log(f"spool: replica r{i} lost "
                f"({self.total - len(lost)}/{self.total} live)")

    def replica_status(self):
        lost = self._state()
        return {"total": self.total, "live": self.total - len(lost),
                "lost": sorted(lost)}

    # -- seq assignment ------------------------------------------------
    def _next_seq(self, stream):
        p = os.path.join(self.spool, f".seq.{stream}")
        try:
            with open(p) as f:
                n = int(f.read().strip() or 0)
        except (OSError, ValueError):
            n = 0
        n += 1
        _atomic_write(p, str(n).encode())
        return n

    # -- the replicated log --------------------------------------------
    def append(self, stream, rec):
        with self._spool_lock():
            lost = self._state()
            seq = self._next_seq(stream)
            frame = {"seq": seq, "crc": _rec_crc(rec), "rec": rec}
            acks, took = 0, []
            for i in range(self.total):
                if i in lost:
                    continue     # a lost replica rejoins via heal(),
                    #              never via fresh appends (it would
                    #              hold a gapped history)
                try:
                    if not os.path.isdir(self._replica_dir(i)):
                        raise OSError(f"replica r{i} gone")
                    path = self._frame_path(i, stream)
                    try:
                        pre = os.path.getsize(path)
                    except OSError:
                        pre = 0
                    _fsync_append(path, frame)
                    acks += 1
                    took.append((path, pre))
                except OSError:
                    self._mark_lost(i, lost)
            if acks < self.write_quorum:
                # the append FAILED: roll the minority writes back so
                # the unacknowledged record can never surface on a
                # later read (the caller was told it did not happen)
                for path, pre in took:
                    try:
                        os.truncate(path, pre)
                    except OSError:
                        pass
                raise SpoolError(
                    f"write quorum lost: {acks}/{self.total} replicas "
                    f"acked (need {self.write_quorum})")

    def read(self, stream, cursor=None):
        """Merge-read: per-replica tails (torn lines held back PER
        replica), any CRC-valid copy of a seq serves, records are
        delivered in seq order exactly once per cursor chain.  A seq
        gap is a crashed un-acked append — skipped, because it was
        never acknowledged to anyone."""
        cur = cursor or {"seq": 0, "off": {}}
        last_seq = int(cur.get("seq", 0))
        offs = dict(cur.get("off", {}))
        lost = self._state()
        fresh = {}                   # seq -> rec
        for i in range(self.total):
            if i in lost:
                continue
            key = str(i)
            lines, offs[key] = _read_new_lines(
                self._frame_path(i, stream), offs.get(key, 0))
            for line in lines:
                try:
                    frame = json.loads(line)
                    seq = int(frame["seq"])
                    rec = frame["rec"]
                    crc = int(frame["crc"])
                except (ValueError, KeyError, TypeError):
                    continue
                if seq <= last_seq or seq in fresh:
                    continue     # another replica already served it
                if _rec_crc(rec) != crc:
                    continue     # bit-rotted copy: try a sibling's
                fresh[seq] = rec
        out = [fresh[s] for s in sorted(fresh)]
        if fresh:
            last_seq = max(fresh)
        return out, {"seq": last_seq, "off": offs}

    # -- replicated blobs ----------------------------------------------
    def _blob_dirs(self):
        lost = self._state()
        dirs = [os.path.join(self._replica_dir(i), "blobs")
                for i in range(self.total) if i not in lost]
        return dirs or [os.path.join(self.spool, "blobs")]

    # -- anti-entropy --------------------------------------------------
    def maintain(self, log=None):
        """Loss detection + anti-entropy heal, under the spool lock.

        A replica whose directory vanished is marked lost (journaled
        ``replica_lost``) even if no append has tripped over it yet; a
        LOST replica whose directory exists again is caught up — its
        surviving valid frame prefix is kept, every missing acked
        record is re-framed onto its tail, blobs are re-replicated —
        and unmarked (journaled ``replica_rejoin``)."""
        events = []
        with self._spool_lock():
            lost = self._state()
            for i in range(self.total):
                present = os.path.isdir(self._replica_dir(i))
                if i not in lost and not present:
                    self._mark_lost(i, lost, log=log)
                    events.append("replica_lost")
                elif i in lost and present:
                    healed = self._heal_one(i)
                    lost.discard(i)
                    self._set_state(lost)
                    self._event("replica_rejoin", replica=i,
                                records=healed,
                                live=self.total - len(lost),
                                total=self.total)
                    events.append("replica_rejoin")
                    if log:
                        log(f"spool: replica r{i} rejoined "
                            f"(+{healed} records healed, "
                            f"{self.total - len(lost)}/{self.total} "
                            f"live)")
        return events

    def _streams(self):
        names = set()
        for i in range(self.total):
            try:
                for f in os.listdir(self._replica_dir(i)):
                    if f.endswith(".jsonl"):
                        names.add(f[:-len(".jsonl")])
            except OSError:
                continue
        return sorted(names)

    def _heal_one(self, i):
        """Catch replica ``i`` up from its live siblings.  Appends
        only the MISSING frames after its surviving valid prefix —
        never rewrites history, so a reader's byte offset into the
        rejoined file stays valid."""
        healed = 0
        lost = self._state()
        for stream in self._streams():
            # the merged view of every OTHER live replica
            merged = {}
            for j in range(self.total):
                if j == i or j in lost:
                    continue
                lines, _ = _read_new_lines(
                    self._frame_path(j, stream), 0)
                for line in lines:
                    try:
                        frame = json.loads(line)
                        seq = int(frame["seq"])
                        if _rec_crc(frame["rec"]) != int(frame["crc"]):
                            continue
                    except (ValueError, KeyError, TypeError):
                        continue
                    merged.setdefault(seq, frame)
            # the rejoining replica's own surviving valid frames
            path = self._frame_path(i, stream)
            have = set()
            lines, valid_end = _read_new_lines(path, 0)
            for line in lines:
                try:
                    frame = json.loads(line)
                    if _rec_crc(frame["rec"]) == int(frame["crc"]):
                        have.add(int(frame["seq"]))
                except (ValueError, KeyError, TypeError):
                    continue
            # drop a torn tail so healed frames append onto a clean
            # line boundary
            try:
                if os.path.getsize(path) > valid_end:
                    with open(path, "r+") as f:
                        f.truncate(valid_end)
            except OSError:
                pass
            for seq in sorted(merged):
                if seq in have:
                    continue
                _fsync_append(path, merged[seq])
                healed += 1
        # blobs: re-replicate whatever the live siblings hold
        for j in range(self.total):
            if j == i or j in lost:
                continue
            src = os.path.join(self._replica_dir(j), "blobs")
            dst = os.path.join(self._replica_dir(i), "blobs")
            try:
                names = [n for n in os.listdir(src)
                         if not n.endswith(".crc")]
            except OSError:
                continue
            os.makedirs(dst, exist_ok=True)
            for n in names:
                if os.path.exists(os.path.join(dst, n)):
                    continue
                try:
                    with open(os.path.join(src, n), "rb") as f:
                        data = f.read()
                    _atomic_write(os.path.join(dst, n), data)
                    _atomic_write(
                        os.path.join(dst, n + ".crc"),
                        str(zlib.crc32(data) & 0xFFFFFFFF).encode())
                except OSError:
                    continue
        return healed
