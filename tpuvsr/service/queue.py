"""Durable job queue for the verification dispatch service.

One spool directory holds the whole queue state, persisted through a
pluggable **spool driver** (``tpuvsr/service/spooldrv.py``, ROADMAP
item 2(b)) so the same queue runs over one POSIX filesystem, an
object-store shape, or a tiny quorum-replicated log:

* the ``jobs`` record stream — an append-only, fsync-per-line spool of
  job records and state transitions.  The queue's in-memory view is a
  pure fold over this log, so a killed worker (or a killed submitter)
  leaves a valid prefix and the next ``JobQueue(spool)`` reconstructs
  exactly the surviving state — the same crash contract as the run
  journal (``tpuvsr/obs/journal.py``).
* **claims** — the driver's conditional-put primitive: exactly one
  claimer wins (``O_CREAT|O_EXCL``-style link dance on ``fs``,
  compare-and-swap records on ``objstore``/``quorum``).  A claim
  carries the attempt **epoch**, and while this queue object holds a
  claim every state append it makes for that job is **fenced** on the
  epoch — a zombie worker whose claim was recovered (and possibly
  re-issued) can never commit a terminal state
  (:class:`~.spooldrv.FencedError`, journaled ``fence``).  Liveness is
  judged pid-first on the claimer's own host and by the driver's
  explicit heartbeat records across hosts (mtime is an ``fs``-only
  legacy fallback); a dead claim is the tombstone of a killed worker,
  and ``recover_stale`` turns those back into claimable jobs — with
  the job's latest snapshot attached as a rescue, so the next attempt
  RESUMES instead of restarting (``checkpoint.snapshot_info``; on
  replicated drivers the snapshot is also driver-held, so it survives
  the claiming host's disk).
* **host leases** — pool parents heartbeat their host identity through
  the driver (``host_heartbeat``), so a survivor host's
  ``recover_stale`` sweeps an ENTIRE dead host's claims at once
  instead of waiting out each claim's own heartbeat window.

Job lifecycle (ISSUE 6; the legal-transition table below is enforced,
an illegal transition is a bug, not a log line):

    queued ──admit──> admitted ──claim──> running ──> done
       │                 │                   │    ├─> violated
       │(lint reject)    │                   │    ├─> failed
       └───> failed      └──> cancelled      │    └─> cancelled
                                             │
                              preempted-requeued <──┘ (exit 75 /
                                    │    rescue checkpoint attached)
                                    └──claim──> running   (again)

Admission (``queued -> admitted``) is where the speclint gate runs —
before any device time is spent (the worker performs it, because only
the worker can load specs; the queue just records the verdict).  The
terminal states are exactly the images of the unified exit-code table
(``tpuvsr/exitcodes.py``).

This module deliberately imports neither jax nor the engines, so the
``submit`` / ``status`` / ``cancel`` CLI verbs stay milliseconds.
"""

from __future__ import annotations

import io
import os
import socket
import tarfile
import threading
import time
import uuid
from dataclasses import dataclass, field

from .spooldrv import (FencedError, SpoolError,  # noqa: F401 — re-export
                       current_host, open_driver)

#: this process's DEFAULT host identity (claims actually record
#: ``spooldrv.current_host()``, which honors the ``TPUVSR_HOST``
#: override fault drills use to fake a multi-host fleet on one box)
HOSTNAME = socket.gethostname()

#: a cross-host claim whose last heartbeat record is older than this is
#: dead (generous: a worker runs a background heartbeat thread touching
#: EVERY claim it holds every few seconds — Worker._hb_loop — on top
#: of the level-boundary ticks, so even a multi-minute compile or a
#: light job queued behind the multi-runner stays visibly alive)
HEARTBEAT_TIMEOUT = 300.0

#: every state a job can be in
STATES = ("queued", "admitted", "running", "done", "violated",
          "failed", "preempted-requeued", "cancelled")
#: states a job never leaves
TERMINAL = frozenset(("done", "violated", "failed", "cancelled"))
#: states a worker may claim from
CLAIMABLE = frozenset(("admitted", "preempted-requeued"))

#: the legal-transition table; queue.transition enforces it
LEGAL = {
    "queued": {"admitted", "failed", "cancelled"},
    "admitted": {"running", "cancelled"},
    "running": {"done", "violated", "failed", "preempted-requeued",
                "cancelled"},
    "preempted-requeued": {"running", "cancelled"},
}


@dataclass
class Job:
    """One verification job: a (spec, cfg, engine, flags) tuple plus
    its lifecycle bookkeeping.  ``flags`` carries everything the worker
    threads through to the engines (maxstates, pipeline, inject,
    supervisor knobs, the tier-1 ``stub`` family); ``devices`` is the
    CURRENT device allocation (the scheduler rewrites it on an elastic
    requeue), ``devices_min``/``devices_max`` bound what elastic
    placement may shrink/grow it to."""

    job_id: str
    spec: str
    cfg: str = None
    engine: str = "auto"
    kind: str = "check"   # "check" (BFS) | "sim" (fleet hunt)
    #                     # | "validate" (trace batch) | "shell"
    #: who submitted — the fair-share scheduling unit (ISSUE 14):
    #: deficit-round-robin pop order and weighted quotas group by this;
    #: None is the anonymous tenant (single-user CLI traffic)
    tenant: str = None
    flags: dict = field(default_factory=dict)
    priority: int = 0
    devices: int = 1
    devices_min: int = None
    devices_max: int = None
    state: str = "queued"
    seq: int = 0
    attempts: int = 0
    rescue: dict = None          # latest rescue-checkpoint handoff
    result: dict = None          # terminal result summary
    reason: str = None           # why failed/requeued/cancelled
    submitted_ts: float = 0.0
    updated_ts: float = 0.0
    #: end-to-end correlation id (ISSUE 17): minted at submit, stamped
    #: on every journal event of the job's whole story across the
    #: service / worker / engine process hops.  None on records written
    #: before the telemetry plane existed (old spools fold fine).
    trace_id: str = None

    @property
    def elastic(self):
        """True when the scheduler may reshape this job's device
        allocation: sharded BFS jobs (mesh reshaped through the PR 5
        reshard-on-load resume), fleet-sim jobs (walker fleet resumed
        on the new mesh; walker count rescales at the next round
        boundary, ISSUE 7), and trace-validation jobs (the batch
        validator re-shards its committed candidate frontier onto
        whatever mesh the resume builds, ISSUE 8)."""
        return ((self.engine == "sharded"
                 or self.kind in ("sim", "validate"))
                and (self.devices_min is not None
                     or self.devices_max is not None))

    def to_dict(self):
        return {k: getattr(self, k) for k in (
            "job_id", "spec", "cfg", "engine", "kind", "tenant",
            "flags", "priority", "devices", "devices_min",
            "devices_max", "state", "seq", "attempts", "rescue",
            "result", "reason", "submitted_ts", "updated_ts",
            "trace_id")}


class QueueError(RuntimeError):
    """An illegal queue operation (unknown job, illegal transition)."""


def _pid_alive(pid):
    try:
        os.kill(int(pid), 0)
    except (OSError, ValueError, TypeError):
        return False
    return True


def _locked(fn):
    """Serialize a JobQueue method on the instance RLock — the HTTP
    front and the multi-runner's light-job threads share one queue
    object with the drain loop (ISSUE 14), and the in-memory fold must
    not interleave.  Cross-PROCESS safety is unchanged: the driver's
    append/claim primitives arbitrate that."""
    def wrapper(self, *args, **kwargs):
        with self._lock:
            return fn(self, *args, **kwargs)
    wrapper.__name__ = fn.__name__
    wrapper.__doc__ = fn.__doc__
    return wrapper


class JobQueue:
    """The durable queue over one spool directory (see module doc).

    All mutators append to the spool BEFORE updating the in-memory
    view, so a crash between the two loses nothing (the next load
    replays the log).  Claims are the only non-log state on the ``fs``
    driver (pure record folds everywhere else), and they are
    self-healing via ``recover_stale``.

    ``driver``/``replicas`` select the spool driver on a NEW spool
    (``spooldrv.open_driver``); an existing spool's persisted choice
    always wins, and no choice at all means ``fs`` — which is how
    every pre-driver spool keeps working with no migration."""

    def __init__(self, spool, *, heartbeat_timeout=HEARTBEAT_TIMEOUT,
                 driver=None, replicas=None, host_lease_timeout=None):
        self.spool = os.path.abspath(spool)
        os.makedirs(self.spool, exist_ok=True)
        self.drv = open_driver(self.spool, driver=driver,
                               replicas=replicas)
        #: the fs-layout jobs log; meaningful on the ``fs``/``objstore``
        #: drivers (tests and legacy tools read it directly), merely
        #: vestigial under ``quorum`` (the stream lives in the replicas)
        self.log_path = os.path.join(self.spool, "jobs.jsonl")
        self.claims_dir = self.drv.claims_dir
        self.journals_dir = os.path.join(self.spool, "journals")
        self.metrics_dir = os.path.join(self.spool, "metrics")
        self.ckpt_dir = os.path.join(self.spool, "ckpt")
        for d in (self.journals_dir, self.metrics_dir, self.ckpt_dir):
            os.makedirs(d, exist_ok=True)
        self.heartbeat_timeout = float(heartbeat_timeout)
        #: a host whose lease record is older than this is dead and
        #: ALL its claims are swept at once (defaults to the per-claim
        #: heartbeat window)
        self.host_lease_timeout = (float(host_lease_timeout)
                                   if host_lease_timeout is not None
                                   else float(heartbeat_timeout))
        self._lock = threading.RLock()
        self._jobs = {}
        self._seq = 0
        self._cursor = None          # driver read cursor over "jobs"
        self._held = {}              # job_id -> claim epoch WE hold
        self._blob_depth = {}        # job_id -> last replicated depth
        self.refresh()

    def lock(self):
        """The instance RLock (a context manager) for callers that
        need several queue calls to be one atomic step against
        sibling threads (the HTTP front's read-modify responses)."""
        return self._lock

    # -- log fold ------------------------------------------------------
    @_locked
    def refresh(self):
        """Fold any ``jobs``-stream records appended since the last
        read — how a long-running worker sees jobs submitted by OTHER
        processes (the CLI ``submit`` verb against a live ``serve``).
        Re-applies this process's own appends too; that is harmless
        because the fold of a log prefix in order is deterministic.  A
        torn final line (a writer killed mid-append) is held back by
        the driver until it is completed."""
        recs, self._cursor = self.drv.read("jobs", self._cursor)
        for rec in recs:
            self._apply(rec)

    def _apply(self, rec):
        op = rec.get("op")
        if op == "submit":
            d = dict(rec["job"])
            job = Job(**d)
            self._jobs[job.job_id] = job
            self._seq = max(self._seq, job.seq)
        elif op == "state":
            job = self._jobs.get(rec["job_id"])
            if job is None:
                return
            job.state = rec["state"]
            job.updated_ts = rec.get("ts", job.updated_ts)
            for k in ("attempts", "devices", "rescue", "result",
                      "reason"):
                if k in rec:
                    setattr(job, k, rec[k])

    # -- paths ---------------------------------------------------------
    def journal_path(self, job_id):
        return os.path.join(self.journals_dir, f"{job_id}.jsonl")

    def metrics_path(self, job_id):
        return os.path.join(self.metrics_dir, f"{job_id}.json")

    def checkpoint_path(self, job_id):
        return os.path.join(self.ckpt_dir, job_id)

    # -- reads (locked too: the drain loop iterates these while the
    # multi-runner's light threads fold new spool lines into _jobs) --
    @_locked
    def jobs(self):
        return sorted(self._jobs.values(), key=lambda j: j.seq)

    @_locked
    def get(self, job_id):
        job = self._jobs.get(job_id)
        if job is None:
            raise QueueError(f"unknown job {job_id!r}")
        return job

    @_locked
    def stats(self):
        """Queue-level gauges: job count per state (the service's
        ``status`` verb surfaces these)."""
        out = {s: 0 for s in STATES}
        for j in self._jobs.values():
            out[j.state] += 1
        out["total"] = len(self._jobs)
        return out

    def spool_status(self):
        """The data plane's own health: driver name plus the quorum
        driver's replica census (``None`` replicas on single-store
        drivers) — what ``status`` and the telemetry plane surface."""
        return {"driver": self.drv.name,
                "replicas": self.drv.replica_status()}

    def backlog(self):
        """Jobs waiting for a worker (queued + admitted +
        preempted-requeued) — the depth the guard's high-water
        backpressure judges (ISSUE 18).  Running jobs don't count:
        they hold devices, not queue headroom."""
        return sum(1 for j in self._jobs.values()
                   if j.state in ("queued",) or j.state in CLAIMABLE)

    def cancel_requested(self, job_id):
        return self.drv.cancel_requested(job_id)

    # -- mutators ------------------------------------------------------
    @_locked
    def submit(self, spec, *, cfg=None, engine="auto", kind="check",
               flags=None, priority=0, devices=1, devices_min=None,
               devices_max=None, tenant=None, job_id=None):
        self.refresh()
        if job_id is None:
            job_id = f"j{self._seq + 1:04d}-{uuid.uuid4().hex[:6]}"
        if job_id in self._jobs:
            raise QueueError(f"job id {job_id!r} already exists")
        self._seq += 1
        flags = dict(flags or {})
        # the ORIGINAL device request survives elastic reshaping (the
        # scheduler rewrites job.devices on shrink/grow requeues; grow
        # decisions compare against what was asked for)
        flags.setdefault("devices_requested", int(devices))
        from ..obs.journal import new_trace_id, root_span
        job = Job(job_id=job_id, spec=str(spec), cfg=cfg, engine=engine,
                  kind=kind, tenant=tenant, flags=flags,
                  priority=int(priority), devices=int(devices),
                  devices_min=devices_min, devices_max=devices_max,
                  seq=self._seq, submitted_ts=round(time.time(), 3),
                  updated_ts=round(time.time(), 3),
                  trace_id=new_trace_id())
        self.drv.append("jobs", {"op": "submit", "job": job.to_dict(),
                                 "ts": job.submitted_ts})
        self._jobs[job.job_id] = job
        # a job's journal opens with its submission — the first line
        # of the story every later attempt appends to (obs.journal is
        # jax-free, so submit stays milliseconds).  The trace is minted
        # HERE: this line carries the correlation id every later event
        # of the job's lifecycle repeats (ISSUE 17)
        from ..obs import Journal
        j = Journal(self.journal_path(job.job_id), run_id="svc-submit",
                    trace_id=job.trace_id,
                    span_id=root_span(job.trace_id))
        try:
            j.write("job_submitted", job_id=job.job_id, spec=job.spec,
                    engine=job.engine, priority=job.priority,
                    devices=job.devices, tenant=job.tenant)
        finally:
            j.close()
        return job

    @_locked
    def transition(self, job_id, state, **fields):
        """Move a job to `state`, recording extra fields (attempts /
        devices / rescue / result / reason).  Raises QueueError on an
        illegal move — the state machine is the API contract.

        While THIS queue object holds the job's claim, the append is
        **fenced** on the claim epoch: if the claim was recovered (and
        possibly re-issued) while we were presumed dead, the driver
        rejects the append with :class:`FencedError` instead of letting
        a zombie commit — the split-brain hole mtime heartbeats only
        papered over."""
        self.refresh()
        job = self.get(job_id)
        if state not in STATES:
            raise QueueError(f"unknown state {state!r}")
        if state not in LEGAL.get(job.state, frozenset()):
            raise QueueError(
                f"illegal transition {job.state!r} -> {state!r} "
                f"for job {job_id}")
        rec = {"op": "state", "job_id": job_id, "state": state,
               "ts": round(time.time(), 3)}
        rec.update(fields)
        epoch = self._held.get(job_id)
        if epoch is not None:
            try:
                self.drv.append_fenced("jobs", rec, job_id=job_id,
                                       epoch=epoch)
            except FencedError:
                # the claim is no longer ours — drop the hold so later
                # calls on this object don't keep fencing against it
                self._held.pop(job_id, None)
                raise
        else:
            self.drv.append("jobs", rec)
        self._apply(rec)
        return job

    # -- claims --------------------------------------------------------
    @_locked
    def claim(self, job_id, owner="worker"):
        """Atomically claim a CLAIMABLE job: the driver's
        conditional-put decides races; the winner transitions the job
        to running (attempt count bumped).  Returns the Job, or None
        on ANY lost race — another holder's claim, or the job left the
        claimable states between our look and our claim (a concurrent
        worker or a ``cancel``).  A lost race is normal multi-worker
        traffic, never an error.  The claim records pid + worker-id
        (`owner`) + host + the attempt **epoch** every later append by
        this holder is fenced on; its explicit heartbeat records are
        what ``recover_stale`` judges cross-host liveness by."""
        self.refresh()
        job = self.get(job_id)
        if job.state not in CLAIMABLE:
            return None
        epoch = job.attempts + 1
        if not self.drv.try_claim(job_id, owner=owner, epoch=epoch):
            return None
        # the claim is ours; re-read the log before announcing — a
        # transition that landed while we were claiming (e.g. a
        # cancel, a concurrent worker at another epoch) wins, and we
        # back out
        self._held[job_id] = epoch
        self.refresh()
        job = self.get(job_id)
        try:
            if job.state not in CLAIMABLE or job.attempts + 1 != epoch:
                raise QueueError("lost the claim race")
            self.transition(job_id, "running", attempts=epoch)
        except (QueueError, FencedError):
            self._held.pop(job_id, None)
            self.drv.release_claim(job_id, epoch=epoch)
            return None
        return job

    @_locked
    def claim_next(self, owner="worker", order=None):
        """Claim the best claimable job.  ``order`` is the pop-order
        policy hook (claimable jobs -> ordered list) — the serving
        tier passes ``FairSharePolicy.order`` (deficit round robin
        over tenants + priority aging, ISSUE 14); without one the
        original greedy order applies (highest priority, then
        submission order)."""
        self.refresh()
        claimable = [j for j in self._jobs.values()
                     if j.state in CLAIMABLE]
        if order is not None:
            ordered = order(claimable)
        else:
            ordered = sorted(claimable,
                             key=lambda j: (-j.priority, j.seq))
        for job in ordered:
            got = self.claim(job.job_id, owner=owner)
            if got is not None:
                return got
        return None

    def heartbeat(self, job_id):
        """Record a liveness heartbeat on the claim — the signal a
        worker sends while it holds a job (every level-boundary tick
        and every shell poll slice).  Returns False when the claim is
        gone (job finished/requeued under us); cheap enough to call
        unconditionally."""
        return self.drv.heartbeat(job_id)

    def release(self, job_id):
        """Drop the claim + cancel marker.  A HOLDER's release is
        conditional on its own epoch (a zombie's release can never
        drop a successor's claim); a non-holder's (recover sweeps)
        is unconditional."""
        self.drv.release_claim(job_id,
                               epoch=self._held.pop(job_id, None))
        self.drv.clear_cancel(job_id)

    # -- endings -------------------------------------------------------
    @_locked
    def finish(self, job_id, state, *, result=None, reason=None):
        if state not in TERMINAL:
            raise QueueError(f"finish wants a terminal state, "
                             f"not {state!r}")
        job = self.transition(job_id, state, result=result,
                              reason=reason)
        self.release(job_id)
        return job

    @_locked
    def requeue(self, job_id, *, reason, rescue=None, devices=None,
                uncount=False):
        """running -> preempted-requeued: the job goes back on the
        queue with its rescue-checkpoint handoff attached (the next
        attempt resumes, not restarts).  ``devices`` lets the scheduler
        reshape an elastic job's next mesh; ``uncount`` refunds the
        attempt (a failure that never really ran, e.g. a tunnel
        flap)."""
        job = self.get(job_id)
        fields = {"reason": reason}
        if rescue is not None:
            fields["rescue"] = rescue
        if devices is not None:
            fields["devices"] = int(devices)
        if uncount:
            fields["attempts"] = max(0, job.attempts - 1)
        job = self.transition(job_id, "preempted-requeued", **fields)
        self.release(job_id)
        return job

    @_locked
    def cancel(self, job_id):
        """Cancel a job.  Non-running jobs cancel immediately; a
        RUNNING job gets a cancel marker the worker polls at level
        boundaries (it preempts the run, then finishes the job as
        cancelled) — so cancel is honored without killing the worker
        mid-level.  Returns the (possibly still-running) Job."""
        self.refresh()
        job = self.get(job_id)
        if job.state in TERMINAL:
            raise QueueError(f"job {job_id} is already terminal "
                             f"({job.state})")
        if job.state == "running" or \
                self.drv.claim_info(job_id) is not None:
            # a claim holder (running, or mid-claim in another
            # process) owns this job's transitions — leave a marker
            # it polls instead of yanking the state out from under it
            self.drv.set_cancel(job_id)
            return job
        return self.finish(job_id, "cancelled", reason="cancelled")

    # -- snapshot handoff ----------------------------------------------
    def replicate_snapshot(self, job_id):
        """Ship the job's latest checkpoint into the driver's blob
        store, so a rescue survives the claiming HOST's disk (the
        host-death-failover story).  No-op on ``fs`` (the spool IS
        the only store) and until the snapshot's depth advances past
        the last shipped copy.  Returns True when a copy shipped."""
        if self.drv.name == "fs":
            return False
        from ..engine.checkpoint import snapshot_info
        path = self.checkpoint_path(job_id)
        info = snapshot_info(path)
        if info is None or self._blob_depth.get(job_id) == \
                info["depth"]:
            return False
        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w") as tar:
            for name in sorted(os.listdir(path)):
                p = os.path.join(path, name)
                if os.path.isfile(p):
                    tar.add(p, arcname=name)
        self.drv.put_blob(f"ckpt-{job_id}.tar", buf.getvalue())
        self._blob_depth[job_id] = info["depth"]
        return True

    def _rescue_info(self, job_id):
        """The rescue handoff for a recovered job: the local snapshot
        manifest when one is readable, else (on replicated drivers)
        the driver-held blob restored into the checkpoint path — how
        a SURVIVOR host resumes a job whose snapshot it never wrote."""
        from ..engine.checkpoint import snapshot_info
        path = self.checkpoint_path(job_id)
        info = snapshot_info(path)
        if info is not None or self.drv.name == "fs":
            return info
        data = self.drv.get_blob(f"ckpt-{job_id}.tar")
        if data is None:
            return None
        os.makedirs(path, exist_ok=True)
        try:
            with tarfile.open(fileobj=io.BytesIO(data)) as tar:
                try:
                    tar.extractall(path, filter="data")
                except TypeError:    # pre-3.12 tarfile: no filter=
                    tar.extractall(path)
        except (OSError, tarfile.TarError):
            return None
        return snapshot_info(path)

    # -- crash recovery ------------------------------------------------
    def host_heartbeat(self, host=None):
        """Write one host-lease heartbeat through the driver — called
        from the pool parent's supervision loop, so the whole host's
        liveness is visible to peers independently of any one claim."""
        self.drv.host_heartbeat(host)

    def dead_hosts(self, now=None):
        """Hosts whose lease record has gone stale — every claim from
        one of these is swept by ``recover_stale`` in one pass.  Hosts
        that never wrote a lease (legacy pools, bare Workers) are
        simply absent: their claims fall back to per-claim liveness."""
        now = time.time() if now is None else now
        return {h for h, lease in self.drv.hosts().items()
                if now - lease["ts"] > self.host_lease_timeout}

    def _claim_alive(self, job_id, dead_hosts=()):
        """Liveness of one claim: ``(alive, info)``.

        Same-host claims are judged by their pid (authoritative and
        instant — a dead pid is recovered without waiting out any
        heartbeat window).  A claim from ANOTHER host has no visible
        pid: if that host's LEASE is stale the claim is dead with the
        whole host (the one-sweep failover path); otherwise the
        driver's explicit heartbeat records decide — fresh
        (< ``heartbeat_timeout``) means a live worker elsewhere holds
        the job and it is never stolen."""
        info = self.drv.claim_info(job_id)
        if info is None:
            return False, {}
        host = info.get("host")
        if host is None or host == current_host():
            return _pid_alive(info.get("pid")), info
        if host in dead_hosts:
            return False, info
        age = self.drv.claim_age(job_id)
        if age is None:
            return False, info
        return age < self.heartbeat_timeout, info

    @_locked
    def recover_stale(self, log=None):
        """Requeue running jobs whose claiming worker died (claim
        missing, or judged dead by ``_claim_alive`` — dead pid on this
        host, stale heartbeat or dead host lease from another).  The
        job's latest snapshot — a periodic checkpoint, the rescue the
        dying worker managed to write, or the driver-held replica of
        either — is attached as the rescue handoff, so the next
        attempt resumes bit-identically instead of restarting (the
        PR 4/5 equivalence contract).  Also runs the driver's own
        housekeeping (replica loss detection + anti-entropy heal on
        ``quorum``)."""
        self.drv.maintain(log=log)
        self.refresh()
        dead = self.dead_hosts()
        recovered = []
        for job in list(self._jobs.values()):
            alive, info = self._claim_alive(job.job_id,
                                            dead_hosts=dead)
            if job.state in CLAIMABLE and info and not alive:
                # a worker died in the window between creating the
                # claim and appending the `running` transition: the
                # orphan claim would block every future claim()
                # forever — clear it (the job itself never started)
                self.drv.release_claim(job.job_id)
                if log:
                    log(f"queue: cleared orphan claim of "
                        f"{job.job_id} (worker died before the "
                        f"running transition)")
                continue
            if job.state != "running":
                continue
            if alive:
                continue
            rescue = self._rescue_info(job.job_id)
            try:
                self.requeue(job.job_id, reason="worker-died",
                             rescue=rescue)
            except (QueueError, FencedError):
                # another recovering worker got there first — a lost
                # race, same as a lost claim
                continue
            # the recovery is part of the job's story: journal the
            # requeue (the worker's own requeue path does the same),
            # naming the dead claim's worker/host
            from ..obs import Journal
            from ..obs.journal import root_span
            jr = Journal(self.journal_path(job.job_id),
                         run_id="svc-recover",
                         trace_id=job.trace_id,
                         span_id=(root_span(job.trace_id)
                                  if job.trace_id else None))
            try:
                jr.write("job_requeued", job_id=job.job_id,
                         reason="worker-died", rescue=rescue,
                         elapsed_s=round(
                             time.time() - job.submitted_ts, 3),
                         dead_worker=info.get("owner"),
                         dead_host=info.get("host"))
            finally:
                jr.close()
            recovered.append(job.job_id)
            if log:
                who = info.get("owner") or "worker"
                where = info.get("host") or current_host()
                log(f"queue: job {job.job_id} had a dead claim "
                    f"({who}@{where}); requeued"
                    + (f" with rescue at depth {rescue['depth']}"
                       if rescue else " (no snapshot — restart)"))
        return recovered
