"""Durable on-disk job queue for the verification dispatch service.

One spool directory holds the whole queue state, in two pieces chosen
so that EVERY mutation is crash-safe without a database:

* ``jobs.jsonl`` — an append-only, fsync-per-line JSONL spool of job
  records and state transitions.  The queue's in-memory view is a pure
  fold over this log, so a killed worker (or a killed submitter)
  leaves a valid prefix and the next ``JobQueue(spool)`` reconstructs
  exactly the surviving state — the same crash contract as the run
  journal (``tpuvsr/obs/journal.py``).
* ``claims/<job_id>.claim`` — atomic claim files.  A worker takes a
  job by creating its claim file with ``O_CREAT|O_EXCL`` (the POSIX
  mutual-exclusion primitive: exactly one creator wins), records its
  pid, worker-id and host inside, and deletes it when the job leaves
  ``running``.  The file's **mtime is the worker's heartbeat**
  (``heartbeat``, touched at every level-boundary tick): liveness is
  judged pid-first on the claimer's own host and heartbeat-first
  across hosts — a live worker on another host (fresh mtime, invisible
  pid) is never mistaken for dead (ISSUE 14 hardening; the old
  dead-pid check was single-host only).  A dead claim is the tombstone
  of a killed worker; ``recover_stale`` turns those back into
  claimable jobs — with the job's latest snapshot attached as a
  rescue, so the next attempt RESUMES instead of restarting
  (``checkpoint.snapshot_info``).

Job lifecycle (ISSUE 6; the legal-transition table below is enforced,
an illegal transition is a bug, not a log line):

    queued ──admit──> admitted ──claim──> running ──> done
       │                 │                   │    ├─> violated
       │(lint reject)    │                   │    ├─> failed
       └───> failed      └──> cancelled      │    └─> cancelled
                                             │
                              preempted-requeued <──┘ (exit 75 /
                                    │    rescue checkpoint attached)
                                    └──claim──> running   (again)

Admission (``queued -> admitted``) is where the speclint gate runs —
before any device time is spent (the worker performs it, because only
the worker can load specs; the queue just records the verdict).  The
terminal states are exactly the images of the unified exit-code table
(``tpuvsr/exitcodes.py``).

This module deliberately imports neither jax nor the engines, so the
``submit`` / ``status`` / ``cancel`` CLI verbs stay milliseconds.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
import uuid
from dataclasses import dataclass, field

#: this process's host identity, recorded in claim files so stale-claim
#: recovery can tell "my host, dead pid" from "another host entirely"
HOSTNAME = socket.gethostname()

#: a cross-host claim whose heartbeat mtime is older than this is dead
#: (generous: a worker runs a background heartbeat thread touching
#: EVERY claim it holds every few seconds — Worker._hb_loop — on top
#: of the level-boundary ticks, so even a multi-minute compile or a
#: light job queued behind the multi-runner stays visibly alive)
HEARTBEAT_TIMEOUT = 300.0

#: every state a job can be in
STATES = ("queued", "admitted", "running", "done", "violated",
          "failed", "preempted-requeued", "cancelled")
#: states a job never leaves
TERMINAL = frozenset(("done", "violated", "failed", "cancelled"))
#: states a worker may claim from
CLAIMABLE = frozenset(("admitted", "preempted-requeued"))

#: the legal-transition table; queue.transition enforces it
LEGAL = {
    "queued": {"admitted", "failed", "cancelled"},
    "admitted": {"running", "cancelled"},
    "running": {"done", "violated", "failed", "preempted-requeued",
                "cancelled"},
    "preempted-requeued": {"running", "cancelled"},
}


@dataclass
class Job:
    """One verification job: a (spec, cfg, engine, flags) tuple plus
    its lifecycle bookkeeping.  ``flags`` carries everything the worker
    threads through to the engines (maxstates, pipeline, inject,
    supervisor knobs, the tier-1 ``stub`` family); ``devices`` is the
    CURRENT device allocation (the scheduler rewrites it on an elastic
    requeue), ``devices_min``/``devices_max`` bound what elastic
    placement may shrink/grow it to."""

    job_id: str
    spec: str
    cfg: str = None
    engine: str = "auto"
    kind: str = "check"   # "check" (BFS) | "sim" (fleet hunt)
    #                     # | "validate" (trace batch) | "shell"
    #: who submitted — the fair-share scheduling unit (ISSUE 14):
    #: deficit-round-robin pop order and weighted quotas group by this;
    #: None is the anonymous tenant (single-user CLI traffic)
    tenant: str = None
    flags: dict = field(default_factory=dict)
    priority: int = 0
    devices: int = 1
    devices_min: int = None
    devices_max: int = None
    state: str = "queued"
    seq: int = 0
    attempts: int = 0
    rescue: dict = None          # latest rescue-checkpoint handoff
    result: dict = None          # terminal result summary
    reason: str = None           # why failed/requeued/cancelled
    submitted_ts: float = 0.0
    updated_ts: float = 0.0
    #: end-to-end correlation id (ISSUE 17): minted at submit, stamped
    #: on every journal event of the job's whole story across the
    #: service / worker / engine process hops.  None on records written
    #: before the telemetry plane existed (old spools fold fine).
    trace_id: str = None

    @property
    def elastic(self):
        """True when the scheduler may reshape this job's device
        allocation: sharded BFS jobs (mesh reshaped through the PR 5
        reshard-on-load resume), fleet-sim jobs (walker fleet resumed
        on the new mesh; walker count rescales at the next round
        boundary, ISSUE 7), and trace-validation jobs (the batch
        validator re-shards its committed candidate frontier onto
        whatever mesh the resume builds, ISSUE 8)."""
        return ((self.engine == "sharded"
                 or self.kind in ("sim", "validate"))
                and (self.devices_min is not None
                     or self.devices_max is not None))

    def to_dict(self):
        return {k: getattr(self, k) for k in (
            "job_id", "spec", "cfg", "engine", "kind", "tenant",
            "flags", "priority", "devices", "devices_min",
            "devices_max", "state", "seq", "attempts", "rescue",
            "result", "reason", "submitted_ts", "updated_ts",
            "trace_id")}


class QueueError(RuntimeError):
    """An illegal queue operation (unknown job, illegal transition)."""


def _fsync_append(path, rec):
    """Append one JSON line durably (the jobs.jsonl write primitive).

    Repairs a torn tail first: a writer killed mid-append leaves a
    partial line with no trailing newline, and appending straight onto
    it would MERGE two records into one garbage line (losing the valid
    one).  Terminating the torn fragment turns it into its own
    invalid, skipped line instead."""
    data = (json.dumps(rec, sort_keys=True, default=str)
            + "\n").encode()
    fd = os.open(path, os.O_CREAT | os.O_WRONLY | os.O_APPEND, 0o644)
    try:
        # torn-tail check via the same fd's file: a crashed writer's
        # partial record is STATIC (every live writer appends with one
        # O_APPEND write syscall, which local filesystems apply
        # atomically — no mid-flight interleaving to race with)
        try:
            with open(path, "rb") as rf:
                rf.seek(0, os.SEEK_END)
                if rf.tell() > 0:
                    rf.seek(-1, os.SEEK_END)
                    if rf.read(1) != b"\n":
                        os.write(fd, b"\n")
        except OSError:
            pass
        # ONE write syscall: concurrent appenders (submit while serve)
        # can never interleave inside each other's records
        os.write(fd, data)
        os.fsync(fd)
    finally:
        os.close(fd)


def _pid_alive(pid):
    try:
        os.kill(int(pid), 0)
    except (OSError, ValueError, TypeError):
        return False
    return True


def _locked(fn):
    """Serialize a JobQueue method on the instance RLock — the HTTP
    front and the multi-runner's light-job threads share one queue
    object with the drain loop (ISSUE 14), and the in-memory fold must
    not interleave.  Cross-PROCESS safety is unchanged: the spool's
    O_APPEND writes and O_EXCL claim files arbitrate that."""
    def wrapper(self, *args, **kwargs):
        with self._lock:
            return fn(self, *args, **kwargs)
    wrapper.__name__ = fn.__name__
    wrapper.__doc__ = fn.__doc__
    return wrapper


class JobQueue:
    """The durable queue over one spool directory (see module doc).

    All mutators append to the spool BEFORE updating the in-memory
    view, so a crash between the two loses nothing (the next load
    replays the log).  Claim files are the only non-log state, and
    they are self-healing via ``recover_stale``."""

    def __init__(self, spool, *, heartbeat_timeout=HEARTBEAT_TIMEOUT):
        self.spool = os.path.abspath(spool)
        self.log_path = os.path.join(self.spool, "jobs.jsonl")
        self.claims_dir = os.path.join(self.spool, "claims")
        self.journals_dir = os.path.join(self.spool, "journals")
        self.metrics_dir = os.path.join(self.spool, "metrics")
        self.ckpt_dir = os.path.join(self.spool, "ckpt")
        for d in (self.spool, self.claims_dir, self.journals_dir,
                  self.metrics_dir, self.ckpt_dir):
            os.makedirs(d, exist_ok=True)
        self.heartbeat_timeout = float(heartbeat_timeout)
        self._lock = threading.RLock()
        self._jobs = {}
        self._seq = 0
        self._log_pos = 0
        self.refresh()

    def lock(self):
        """The instance RLock (a context manager) for callers that
        need several queue calls to be one atomic step against
        sibling threads (the HTTP front's read-modify responses)."""
        return self._lock

    # -- log fold ------------------------------------------------------
    @_locked
    def refresh(self):
        """Fold any spool lines appended since the last read — how a
        long-running worker sees jobs submitted by OTHER processes
        (the CLI ``submit`` verb against a live ``serve``).  Re-applies
        this process's own appends too; that is harmless because the
        fold of a log prefix in order is deterministic.  A torn final
        line (a writer killed mid-append) is left un-consumed until it
        is completed."""
        try:
            size = os.path.getsize(self.log_path)
        except OSError:
            return
        if size <= self._log_pos:
            return
        with open(self.log_path) as f:
            f.seek(self._log_pos)
            while True:
                line = f.readline()
                if not line:
                    break
                if not line.endswith("\n"):
                    break        # torn tail: re-read next refresh
                self._log_pos = f.tell()
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                self._apply(rec)

    def _apply(self, rec):
        op = rec.get("op")
        if op == "submit":
            d = dict(rec["job"])
            job = Job(**d)
            self._jobs[job.job_id] = job
            self._seq = max(self._seq, job.seq)
        elif op == "state":
            job = self._jobs.get(rec["job_id"])
            if job is None:
                return
            job.state = rec["state"]
            job.updated_ts = rec.get("ts", job.updated_ts)
            for k in ("attempts", "devices", "rescue", "result",
                      "reason"):
                if k in rec:
                    setattr(job, k, rec[k])

    # -- paths ---------------------------------------------------------
    def journal_path(self, job_id):
        return os.path.join(self.journals_dir, f"{job_id}.jsonl")

    def metrics_path(self, job_id):
        return os.path.join(self.metrics_dir, f"{job_id}.json")

    def checkpoint_path(self, job_id):
        return os.path.join(self.ckpt_dir, job_id)

    def _claim_path(self, job_id):
        return os.path.join(self.claims_dir, f"{job_id}.claim")

    def _cancel_marker(self, job_id):
        return os.path.join(self.claims_dir, f"{job_id}.cancel")

    # -- reads (locked too: the drain loop iterates these while the
    # multi-runner's light threads fold new spool lines into _jobs) --
    @_locked
    def jobs(self):
        return sorted(self._jobs.values(), key=lambda j: j.seq)

    @_locked
    def get(self, job_id):
        job = self._jobs.get(job_id)
        if job is None:
            raise QueueError(f"unknown job {job_id!r}")
        return job

    @_locked
    def stats(self):
        """Queue-level gauges: job count per state (the service's
        ``status`` verb surfaces these)."""
        out = {s: 0 for s in STATES}
        for j in self._jobs.values():
            out[j.state] += 1
        out["total"] = len(self._jobs)
        return out

    def backlog(self):
        """Jobs waiting for a worker (queued + admitted +
        preempted-requeued) — the depth the guard's high-water
        backpressure judges (ISSUE 18).  Running jobs don't count:
        they hold devices, not queue headroom."""
        return sum(1 for j in self._jobs.values()
                   if j.state in ("queued",) or j.state in CLAIMABLE)

    def cancel_requested(self, job_id):
        return os.path.exists(self._cancel_marker(job_id))

    # -- mutators ------------------------------------------------------
    @_locked
    def submit(self, spec, *, cfg=None, engine="auto", kind="check",
               flags=None, priority=0, devices=1, devices_min=None,
               devices_max=None, tenant=None, job_id=None):
        self.refresh()
        if job_id is None:
            job_id = f"j{self._seq + 1:04d}-{uuid.uuid4().hex[:6]}"
        if job_id in self._jobs:
            raise QueueError(f"job id {job_id!r} already exists")
        self._seq += 1
        flags = dict(flags or {})
        # the ORIGINAL device request survives elastic reshaping (the
        # scheduler rewrites job.devices on shrink/grow requeues; grow
        # decisions compare against what was asked for)
        flags.setdefault("devices_requested", int(devices))
        from ..obs.journal import new_trace_id, root_span
        job = Job(job_id=job_id, spec=str(spec), cfg=cfg, engine=engine,
                  kind=kind, tenant=tenant, flags=flags,
                  priority=int(priority), devices=int(devices),
                  devices_min=devices_min, devices_max=devices_max,
                  seq=self._seq, submitted_ts=round(time.time(), 3),
                  updated_ts=round(time.time(), 3),
                  trace_id=new_trace_id())
        _fsync_append(self.log_path, {"op": "submit",
                                      "job": job.to_dict(),
                                      "ts": job.submitted_ts})
        self._jobs[job.job_id] = job
        # a job's journal opens with its submission — the first line
        # of the story every later attempt appends to (obs.journal is
        # jax-free, so submit stays milliseconds).  The trace is minted
        # HERE: this line carries the correlation id every later event
        # of the job's lifecycle repeats (ISSUE 17)
        from ..obs import Journal
        j = Journal(self.journal_path(job.job_id), run_id="svc-submit",
                    trace_id=job.trace_id,
                    span_id=root_span(job.trace_id))
        try:
            j.write("job_submitted", job_id=job.job_id, spec=job.spec,
                    engine=job.engine, priority=job.priority,
                    devices=job.devices, tenant=job.tenant)
        finally:
            j.close()
        return job

    @_locked
    def transition(self, job_id, state, **fields):
        """Move a job to `state`, recording extra fields (attempts /
        devices / rescue / result / reason).  Raises QueueError on an
        illegal move — the state machine is the API contract."""
        self.refresh()
        job = self.get(job_id)
        if state not in STATES:
            raise QueueError(f"unknown state {state!r}")
        if state not in LEGAL.get(job.state, frozenset()):
            raise QueueError(
                f"illegal transition {job.state!r} -> {state!r} "
                f"for job {job_id}")
        rec = {"op": "state", "job_id": job_id, "state": state,
               "ts": round(time.time(), 3)}
        rec.update(fields)
        _fsync_append(self.log_path, rec)
        self._apply(rec)
        return job

    # -- claims --------------------------------------------------------
    @_locked
    def claim(self, job_id, owner="worker"):
        """Atomically claim a CLAIMABLE job: O_CREAT|O_EXCL on the
        claim file decides races; the winner transitions the job to
        running (attempt count bumped).  Returns the Job, or None on
        ANY lost race — another holder's claim file, or the job left
        the claimable states between our look and our claim (a
        concurrent worker or a ``cancel``).  A lost race is normal
        multi-worker traffic, never an error.  The claim records
        pid + worker-id (`owner`) + host, and its mtime is the
        heartbeat ``recover_stale`` judges cross-host liveness by."""
        self.refresh()
        job = self.get(job_id)
        if job.state not in CLAIMABLE:
            return None
        path = self._claim_path(job_id)
        # write-then-LINK: the claim file appears fully written or not
        # at all, so a concurrent recover_stale can never read a
        # half-written (pid-less) claim and mistake it for an orphan.
        # The tmp name carries pid AND thread id: two Workers hosted
        # by one process (threads over separate JobQueue instances —
        # their RLocks don't protect each other) must not share a
        # staging file, or the loser's os.link sees it already
        # unlinked (FileNotFoundError, not the race-deciding EEXIST)
        tmp = f"{path}.{os.getpid()}.{threading.get_ident()}.tmp"
        with open(tmp, "w") as f:
            json.dump({"pid": os.getpid(), "owner": owner,
                       "host": HOSTNAME,
                       "ts": round(time.time(), 3)}, f)
            f.flush()
            os.fsync(f.fileno())
        try:
            os.link(tmp, path)      # EEXIST decides the race, like O_EXCL
        except FileExistsError:
            return None
        finally:
            os.unlink(tmp)
        # the claim file is ours; re-read the log before announcing —
        # a transition that landed while we were writing (e.g. a
        # cancel, a concurrent worker) wins, and we back out
        self.refresh()
        job = self.get(job_id)
        try:
            if job.state not in CLAIMABLE:
                raise QueueError("lost the claim race")
            self.transition(job_id, "running",
                            attempts=job.attempts + 1)
        except QueueError:
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass
            return None
        return job

    @_locked
    def claim_next(self, owner="worker", order=None):
        """Claim the best claimable job.  ``order`` is the pop-order
        policy hook (claimable jobs -> ordered list) — the serving
        tier passes ``FairSharePolicy.order`` (deficit round robin
        over tenants + priority aging, ISSUE 14); without one the
        original greedy order applies (highest priority, then
        submission order)."""
        self.refresh()
        claimable = [j for j in self._jobs.values()
                     if j.state in CLAIMABLE]
        if order is not None:
            ordered = order(claimable)
        else:
            ordered = sorted(claimable,
                             key=lambda j: (-j.priority, j.seq))
        for job in ordered:
            got = self.claim(job.job_id, owner=owner)
            if got is not None:
                return got
        return None

    def heartbeat(self, job_id):
        """Touch the claim file's mtime — the liveness signal a worker
        sends while it holds a job (every level-boundary tick and
        every shell poll slice).  Returns False when the claim is gone
        (job finished/requeued under us); cheap enough to call
        unconditionally."""
        try:
            os.utime(self._claim_path(job_id))
        except OSError:
            return False
        return True

    def release(self, job_id):
        for p in (self._claim_path(job_id), self._cancel_marker(job_id)):
            try:
                os.unlink(p)
            except FileNotFoundError:
                pass

    # -- endings -------------------------------------------------------
    @_locked
    def finish(self, job_id, state, *, result=None, reason=None):
        if state not in TERMINAL:
            raise QueueError(f"finish wants a terminal state, "
                             f"not {state!r}")
        job = self.transition(job_id, state, result=result,
                              reason=reason)
        self.release(job_id)
        return job

    @_locked
    def requeue(self, job_id, *, reason, rescue=None, devices=None,
                uncount=False):
        """running -> preempted-requeued: the job goes back on the
        queue with its rescue-checkpoint handoff attached (the next
        attempt resumes, not restarts).  ``devices`` lets the scheduler
        reshape an elastic job's next mesh; ``uncount`` refunds the
        attempt (a failure that never really ran, e.g. a tunnel
        flap)."""
        job = self.get(job_id)
        fields = {"reason": reason}
        if rescue is not None:
            fields["rescue"] = rescue
        if devices is not None:
            fields["devices"] = int(devices)
        if uncount:
            fields["attempts"] = max(0, job.attempts - 1)
        job = self.transition(job_id, "preempted-requeued", **fields)
        self.release(job_id)
        return job

    @_locked
    def cancel(self, job_id):
        """Cancel a job.  Non-running jobs cancel immediately; a
        RUNNING job gets a cancel marker the worker polls at level
        boundaries (it preempts the run, then finishes the job as
        cancelled) — so cancel is honored without killing the worker
        mid-level.  Returns the (possibly still-running) Job."""
        self.refresh()
        job = self.get(job_id)
        if job.state in TERMINAL:
            raise QueueError(f"job {job_id} is already terminal "
                             f"({job.state})")
        if job.state == "running" or \
                os.path.exists(self._claim_path(job_id)):
            # a claim holder (running, or mid-claim in another
            # process) owns this job's transitions — leave a marker
            # it polls instead of yanking the state out from under it
            marker = self._cancel_marker(job_id)
            with open(marker, "w") as f:
                f.write(json.dumps({"ts": round(time.time(), 3)}))
            return job
        return self.finish(job_id, "cancelled", reason="cancelled")

    # -- crash recovery ------------------------------------------------
    def _claim_alive(self, path):
        """Liveness of one claim file: ``(alive, info)``.

        Same-host claims are judged by their pid (authoritative and
        instant — a dead pid is recovered without waiting out any
        heartbeat window, exactly the old behavior).  A claim from
        ANOTHER host has no visible pid, so its heartbeat mtime
        decides: fresh (< ``heartbeat_timeout``) means a live worker
        elsewhere holds the job — never steal it; stale means its host
        died (or lost the shared filesystem) and the job is
        recoverable.  Before ISSUE 14 the pid check ran
        unconditionally, so a cross-host worker whose pid happened to
        be dead *here* was wrongly declared dead."""
        try:
            with open(path) as f:
                info = json.load(f)
        except (OSError, ValueError):
            return False, {}
        host = info.get("host")
        if host is None or host == HOSTNAME:
            return _pid_alive(info.get("pid")), info
        try:
            age = time.time() - os.path.getmtime(path)
        except OSError:
            return False, info
        return age < self.heartbeat_timeout, info

    @_locked
    def recover_stale(self, log=None):
        """Requeue running jobs whose claiming worker died (claim file
        missing, or judged dead by ``_claim_alive`` — dead pid on this
        host, stale heartbeat from another).  The job's latest
        snapshot — a periodic checkpoint or the rescue the dying
        worker managed to write — is attached as the rescue handoff,
        so the next attempt resumes bit-identically instead of
        restarting (the PR 4/5 equivalence contract)."""
        from ..engine.checkpoint import snapshot_info
        self.refresh()
        recovered = []
        for job in list(self._jobs.values()):
            path = self._claim_path(job.job_id)
            alive, info = (self._claim_alive(path)
                           if os.path.exists(path) else (False, {}))
            if job.state in CLAIMABLE and os.path.exists(path) \
                    and not alive:
                # a worker died in the window between creating the
                # claim file and appending the `running` transition:
                # the orphan claim would block every future claim()
                # forever — clear it (the job itself never started)
                os.unlink(path)
                if log:
                    log(f"queue: cleared orphan claim of "
                        f"{job.job_id} (worker died before the "
                        f"running transition)")
                continue
            if job.state != "running":
                continue
            if alive:
                continue
            rescue = snapshot_info(self.checkpoint_path(job.job_id))
            try:
                self.requeue(job.job_id, reason="worker-died",
                             rescue=rescue)
            except QueueError:
                # another recovering worker got there first — a lost
                # race, same as a lost claim
                continue
            # the recovery is part of the job's story: journal the
            # requeue (the worker's own requeue path does the same),
            # naming the dead claim's worker/host
            from ..obs import Journal
            from ..obs.journal import root_span
            jr = Journal(self.journal_path(job.job_id),
                         run_id="svc-recover",
                         trace_id=job.trace_id,
                         span_id=(root_span(job.trace_id)
                                  if job.trace_id else None))
            try:
                jr.write("job_requeued", job_id=job.job_id,
                         reason="worker-died", rescue=rescue,
                         elapsed_s=round(
                             time.time() - job.submitted_ts, 3),
                         dead_worker=info.get("owner"),
                         dead_host=info.get("host"))
            finally:
                jr.close()
            recovered.append(job.job_id)
            if log:
                who = info.get("owner") or "worker"
                where = info.get("host") or HOSTNAME
                log(f"queue: job {job.job_id} had a dead claim "
                    f"({who}@{where}); requeued"
                    + (f" with rescue at depth {rescue['depth']}"
                       if rescue else " (no snapshot — restart)"))
        return recovered
