"""tpuvsr.service — the federated verification dispatch service.

The composition layer (ISSUE 6 tentpole, ROADMAP item 3) that turns
the CLI tool into a long-running dispatcher: everything a job service
needs was already built as parts — supervised resumable runs (PR 3),
elastic reshardable checkpoints (PR 5), the exit-75 preemption
contract, JSONL journals (PR 2), and the speclint admission gate
(PR 1) — and this package composes them, after the "AI-Orchestrated
Proof Dispatch" architecture of *Federated Formal Verification*
(arxiv 2606.02019):

* **queue.py** — durable on-disk job queue: append-only fsync'd JSONL
  spool + atomic ``O_CREAT|O_EXCL`` claim files, job states
  ``queued -> admitted -> running -> {done, violated, failed,
  preempted-requeued}`` (+ ``cancelled``), crash recovery that turns
  a dead worker's claims back into claimable jobs WITH their rescue
  checkpoints attached;
* **scheduler.py** — device pool + greedy bin-pack by requested
  device count, live elastic shrink/grow of sharded runs through the
  PR 5 reshard-on-load resume path, and the cpu-vs-tpu placement
  advisory (``compare_bench`` cross-backend logic);
* **worker.py** — one process hosting many jobs under
  ``resilience.run_supervised`` (library mode): per-job journals and
  metrics docs, speclint admission before any device time, outcome ->
  terminal-state mapping through the ONE exit-code table
  (``tpuvsr/exitcodes.py``);
* **api.py** — the ``serve`` / ``submit`` / ``status`` / ``cancel``
  CLI verbs; per-job journal tail + metrics doc are the query
  surface (the trace-artifact-as-API posture of arxiv 2404.16075).

Tier-1: the whole service runs on the stub harness
(``tpuvsr/testing.py``) — see ``scripts/serve_demo.py`` and
``tests/test_service.py``.
"""

from __future__ import annotations

from .queue import (CLAIMABLE, HEARTBEAT_TIMEOUT, LEGAL, STATES,
                    TERMINAL, Job, JobQueue, QueueError)
from .scheduler import (Decision, DevicePool, Scheduler,
                        advise_backend, detect_tpu_devices,
                        pow2_floor, watch_backend)
from .worker import JobObserver, Worker, result_summary, \
    trace_to_jsonable

__all__ = [
    "Job", "JobQueue", "QueueError", "STATES", "TERMINAL", "CLAIMABLE",
    "LEGAL", "HEARTBEAT_TIMEOUT", "DevicePool", "Scheduler",
    "Decision", "advise_backend",
    "detect_tpu_devices", "pow2_floor", "watch_backend", "Worker",
    "JobObserver",
    "result_summary", "trace_to_jsonable",
]
