"""The dispatch worker: claims jobs, runs them supervised, maps
outcomes back onto the queue.

One worker process hosts MANY jobs (the ``run_supervised`` library
mode, ISSUE 6 satellite — nothing in a job's ending may own the
process exit).  Per job the worker:

1. **admits** — loads the spec and runs the speclint gate
   (``queued -> admitted``, or ``failed`` with the lint findings as
   the reason: a rejected job never costs device time);
2. **claims** (atomic claim file), allocates devices from the
   scheduler's pool (a sharded job's allocation IS its mesh size),
   journals ``job_started``;
3. **runs** under ``resilience.Supervisor`` via ``run_supervised`` —
   OOM degrades (tile halving / mesh shrink / paged fallback) stay
   per-job, the job's journal and metrics doc collect every attempt,
   and a rescue handoff on the queue makes the run resume from its
   snapshot;
4. at every level boundary (a :class:`JobObserver` tick) it polls for
   cancellation and asks the scheduler to **rebalance** — a
   higher-priority arrival or freed devices preempts the run through
   the ordinary rescue-checkpoint path (``request_preemption``: the
   same flag SIGTERM sets, so the machinery is identical to a real
   preemption) and requeues it with the scheduler's new mesh size;
5. **maps the outcome** to a terminal state through the ONE table in
   ``tpuvsr/exitcodes.py`` — exit 75 / ``Preempted`` means
   ``preempted-requeued`` with the rescue checkpoint attached, never
   a dead job.

Jobs with ``flags.stub`` run the inline counter spec through the REAL
device/paged/sharded engines on the stub kernel
(``tpuvsr/testing.py``) — the tier-1 path every service test and
``scripts/serve_demo.py`` exercises without the reference mount.

``kind="shell"`` jobs (argv + timeout) exist for the absorbed
``scripts/tpu_queue.py`` workload driver: same spool, same claim
discipline, same exit-code table — one queue implementation.
"""

from __future__ import annotations

import os
import signal
import subprocess
import threading
import time

from ..exitcodes import EX_RESUMABLE, job_state
from ..obs import Journal, RunObserver
from ..obs.journal import (new_span_id, root_span, trace_env,
                           trace_scope)
from .scheduler import DevicePool, Scheduler, advise_backend

# NOTE: the serving-tier pieces (fair-share policy, multi-runner) live
# in the HIGHER tpuvsr/serve layer and are imported lazily inside the
# Worker — the default policy/lane wiring lives here for one-stop
# construction, but `import tpuvsr.service` must not eagerly drag the
# serving tier in (the dependency arrow stays serve -> service)


def _is_light(job):
    from ..serve.multirunner import is_light
    return is_light(job)


# the ONE trace serializer (engine/trace.py), re-exported under the
# name the service's callers and tests already use
from ..engine.trace import trace_to_jsonable  # noqa: E402,F401


def result_summary(res):
    """CheckResult -> the JSON-able summary stored on the job."""
    out = {"ok": bool(res.ok),
           "distinct": int(res.distinct_states),
           "generated": int(res.states_generated),
           "diameter": int(res.diameter),
           "levels": ([int(x) for x in res.levels]
                      if res.levels else None),
           "violated": res.violated_invariant,
           "error": res.error,
           "elapsed_s": round(float(res.elapsed or 0.0), 3)}
    if res.trace:
        out["trace"] = trace_to_jsonable(res.trace)
    return out


class JobObserver(RunObserver):
    """RunObserver whose ``level_done`` also ticks the worker — the
    hook that makes scheduling LIVE: cancellation and rebalance
    decisions land at level boundaries, exactly where the engines
    poll the preemption flag."""

    def __init__(self, *args, tick=None, **kwargs):
        super().__init__(*args, **kwargs)
        self._tick = tick

    def level_done(self, depth, **kw):
        super().level_done(depth, **kw)
        if self._tick is not None:
            self._tick(int(depth))

    def sim_chunk(self, depth, **kw):
        # fleet chunk boundaries are the sim analog of level
        # boundaries (ISSUE 7): the same tick drives cancel and
        # elastic rebalance for kind="sim" jobs
        super().sim_chunk(depth, **kw)
        if self._tick is not None:
            self._tick(int(depth))

    def validate_chunk(self, depth, **kw):
        # validation chunk boundaries complete the set (ISSUE 8):
        # kind="validate" jobs cancel/rebalance where the batch
        # validator polls the preemption flag
        super().validate_chunk(depth, **kw)
        if self._tick is not None:
            self._tick(int(depth))


class Worker:
    """Serial drain loop over one :class:`JobQueue` (see module doc).

    `on_level(worker, job, depth)` is the test/demo hook invoked at
    every level boundary of a running job BEFORE the scheduler looks —
    the deterministic stand-in for "a job arrives mid-run"."""

    def __init__(self, queue, *, devices=None, scheduler=None,
                 log=None, on_level=None, owner=None, poll=0.25,
                 bench_dir=None, tpu_devices=0, shell_retry_gate=None,
                 policy="auto", light_threads=2,
                 hb_journal_every=30.0, guard=None):
        self.queue = queue
        # the serving-tier admission guard (ISSUE 18): when present,
        # its per-(tenant, spec-digest) circuit breaker is consulted
        # BEFORE any device allocation and fed every terminal outcome
        self.guard = guard
        if devices is None:
            import jax
            devices = len(jax.devices())
        # fair-share pop order (ISSUE 14): "auto" builds the default
        # deficit-round-robin + aging policy; None reverts to the
        # original priority-then-seq order
        if policy == "auto":
            from ..serve.fairshare import FairSharePolicy
            policy = FairSharePolicy()
        self.policy = policy
        self.pool = (scheduler.pool if scheduler
                     else DevicePool(devices))
        self.scheduler = scheduler or Scheduler(self.pool,
                                                policy=self.policy)
        # the light-job side lane (ISSUE 14): shell / interp-validate /
        # lint-only jobs run on threads with a zero-device allocation
        # while this worker's mesh job keeps running; 0 disables
        if light_threads:
            from ..serve.multirunner import MultiRunner
            self.multirunner = MultiRunner(self, threads=light_threads)
        else:
            self.multirunner = None
        self.hb_journal_every = hb_journal_every
        self._last_hb = 0.0
        # every claim this worker currently holds, heartbeated by a
        # background thread — the level-boundary tick alone cannot
        # cover a multi-minute first compile or a light job waiting in
        # the multi-runner's backlog, and a silent claim looks DEAD to
        # a cross-host recover_stale after heartbeat_timeout
        self._held = set()
        self._hb_thread = None
        self._hb_stop = threading.Event()
        self.on_level = on_level
        self.owner = owner or f"worker-{os.getpid()}"
        self.poll = poll
        self.bench_dir = bench_dir
        self.tpu_devices = tpu_devices
        # shell jobs only: gate(job, rc) -> True means the failure
        # never really ran (e.g. a dead tunnel) — refund the attempt
        # and requeue instead of burning one (tpu_queue flap logic)
        self.shell_retry_gate = shell_retry_gate
        self._log = log
        self._specs = {}             # job_id -> loaded spec (admission)
        self._spans = {}             # job_id -> this attempt's span id
        self._current = None
        self._preempt_sent = False
        self._cancelled = False
        self._requeue_devices = None
        self._requeue_reason = None
        self._shutdown = False       # external SIGTERM/SIGINT landed
        self.processed = []          # [(job_id, state), ...] this drain

    def log(self, msg):
        if self._log:
            self._log(f"service: {msg}")

    # -- claim heartbeats ----------------------------------------------
    def _hb_loop(self, interval):
        while not self._hb_stop.wait(interval):
            for jid in list(self._held):
                self.queue.heartbeat(jid)

    def _hold(self, job_id):
        """Track a held claim and make sure the heartbeat thread is
        alive — from here until ``_release_hold`` the claim mtime
        stays fresh no matter what the job is doing (compiling,
        queued behind the light lane, mid-subprocess)."""
        self._held.add(job_id)
        if self._hb_thread is None or not self._hb_thread.is_alive():
            timeout = getattr(self.queue, "heartbeat_timeout", 300.0)
            self._hb_stop.clear()
            self._hb_thread = threading.Thread(
                target=self._hb_loop,
                args=(max(1.0, min(15.0, timeout / 10.0)),),
                name="tpuvsr-heartbeat", daemon=True)
            self._hb_thread.start()

    def _release_hold(self, job_id):
        self._held.discard(job_id)

    def _trace_ctx(self, job):
        """This job's trace context for a service-side journal write:
        the attempt span while one is open (parented on the service
        root), the deterministic root span otherwise.  Jobs from a
        pre-telemetry spool (no trace_id) get no trace keys at all."""
        tid = getattr(job, "trace_id", None)
        if not tid:
            # explicit empty strings so a concurrently exported
            # trace_scope (another job on this process) can never
            # leak its env context into THIS job's events
            return {"trace_id": "", "span_id": "", "parent_span": ""}
        span = self._spans.get(job.job_id)
        if span:
            return {"trace_id": tid, "span_id": span,
                    "parent_span": root_span(tid)}
        return {"trace_id": tid, "span_id": root_span(tid)}

    def _journal(self, job, event, **fields):
        """Append one job_* event to the JOB'S OWN journal (the same
        file the engine/supervisor attempts write to)."""
        j = Journal(self.queue.journal_path(job.job_id),
                    run_id=f"svc-{self.owner}", **self._trace_ctx(job))
        try:
            j.write(event, job_id=job.job_id,
                    elapsed_s=round(time.time() - job.submitted_ts, 3),
                    **fields)
        finally:
            j.close()

    # -- admission (the speclint gate) ---------------------------------
    def _load_spec(self, job):
        if job.flags.get("stub"):
            from ..testing import bad_counter_spec, counter_spec
            if job.flags.get("stub_bad"):
                return bad_counter_spec()
            return counter_spec(
                inv_bound=job.flags.get("inv_bound"),
                inv_x_bound=job.flags.get("inv_x_bound"))
        from ..engine.spec import load_spec
        cfg = job.cfg or os.path.splitext(job.spec)[0] + ".cfg"
        return load_spec(job.spec, cfg)

    def admit_pending(self):
        """queued -> admitted (or failed): load each new job's spec
        and run the full speclint report — rejection happens HERE,
        before any device time is spent.  A QueueError from any
        transition is a lost race against a concurrent worker (same as
        a lost claim): skip, never crash."""
        from .queue import FencedError, QueueError
        for job in [j for j in self.queue.jobs()
                    if j.state == "queued"]:
            try:
                self._admit_one(job)
            except (QueueError, FencedError):
                continue

    def _admit_one(self, job):
        from ..analysis import lint_enabled, run_lint
        if job.kind == "shell":
            self.queue.transition(job.job_id, "admitted")
            self._journal(job, "job_admitted")
            return
        try:
            spec = self._load_spec(job)
        except Exception as e:  # noqa: BLE001 — a job, not the worker
            self.queue.finish(job.job_id, "failed",
                              reason=f"spec-load: "
                                     f"{type(e).__name__}: {e}")
            self._journal(job, "job_done", state="failed",
                          reason="spec-load")
            return
        if not job.flags.get("stub"):
            # the worker's engines (device/paged/sharded) all need
            # a compiled kernel; saying so at admission beats a
            # KeyError out of the model registry mid-claim
            from ..models.registry import has_device_model
            if not has_device_model(spec):
                self.queue.finish(
                    job.job_id, "failed",
                    reason=f"no device kernel for module "
                           f"{spec.module.name!r} "
                           f"(models/registry)")
                self._journal(job, "job_done", state="failed",
                              reason="no-device-kernel")
                return
        if lint_enabled():
            report = run_lint(spec)
            if report.exit_code:
                findings = [f"{f.passname}: {f.message}"
                            for f in report.errors]
                self.queue.finish(job.job_id, "failed",
                                  reason="speclint",
                                  result={"speclint": findings})
                self._journal(job, "job_done", state="failed",
                              reason="speclint")
                self.log(f"job {job.job_id} rejected by speclint "
                         f"({len(findings)} error(s))")
                return
            if self._reject_oversized(job, spec):
                return
        self._specs[job.job_id] = spec
        self.queue.transition(job.job_id, "admitted")
        self._journal(job, "job_admitted")

    def _reject_oversized(self, job, spec):
        """Bounds-pass admission gate (ISSUE 13): a check job whose
        static state-space upper bound provably exceeds the requested
        tier's capacity (``scheduler.tier_states_for``) is rejected —
        with the minimum tier that WOULD fit as the re-advisory —
        before any device time.  Returns True when the job was
        finished (rejected)."""
        if job.kind != "check":
            return False
        try:
            from ..analysis.passes.bounds import analyze
            facts = analyze(spec)
        except Exception:  # noqa: BLE001 — advisory gate, never fatal
            return False
        if facts.state_bound is None:
            return False
        from .scheduler import TIER_STATES_PER_DEVICE, tier_states_for
        cap = tier_states_for(job)
        if facts.state_bound <= cap:
            return False
        advised = -(-facts.state_bound // TIER_STATES_PER_DEVICE)
        self.queue.finish(
            job.job_id, "failed", reason="bounds-admission",
            result={"state_bound": int(facts.state_bound),
                    "tier_states": int(cap),
                    "advised_devices": int(advised)})
        self._journal(job, "job_done", state="failed",
                      reason="bounds-admission")
        self.log(f"job {job.job_id} rejected at admission: static "
                 f"state bound {facts.state_bound} exceeds the "
                 f"requested tier's {cap} states (re-advise: "
                 f">= {advised} device(s) or a paged/spill tier)")
        return True

    # -- the level-boundary tick ---------------------------------------
    def _tick(self, job, depth):
        # heartbeat FIRST, even when a preemption is already pending:
        # the claim heartbeat record is what keeps a cross-host
        # recover_stale from declaring this worker dead (ISSUE 14)
        self.queue.heartbeat(job.job_id)
        try:
            # on replicated drivers, ship the latest snapshot into the
            # driver blob store so a rescue survives THIS host's disk
            # (no-op on fs, and until the snapshot depth advances)
            self.queue.replicate_snapshot(job.job_id)
        except Exception:  # noqa: BLE001 — replication is best-effort
            pass
        if self.hb_journal_every and \
                time.time() - self._last_hb >= self.hb_journal_every:
            self._last_hb = time.time()
            self._journal(job, "worker_heartbeat", worker=self.owner,
                          depth=int(depth))
        if self._preempt_sent:
            return
        from ..resilience.supervisor import request_preemption
        # fold spool lines appended by OTHER processes since the last
        # look — live admission/rebalance must see a `submit` from a
        # second terminal, not just jobs entered through this object
        self.queue.refresh()
        if self.queue.cancel_requested(job.job_id):
            self._cancelled = True
            self._preempt_sent = True
            request_preemption("CANCEL")
            self.log(f"job {job.job_id}: cancel requested; rescuing "
                     f"at the level boundary")
            return
        if self.on_level is not None:
            self.on_level(self, job, depth)
        self.admit_pending()
        dec = self.scheduler.rebalance(job, self.queue.jobs())
        if dec is not None:
            self._requeue_devices = dec.devices
            self._requeue_reason = f"{dec.action}: {dec.reason}"
            self._preempt_sent = True
            request_preemption("SCHED")
            self.log(f"job {job.job_id}: {self._requeue_reason}; "
                     f"preempting at the level boundary "
                     f"(next mesh {dec.devices})")

    # -- one job -------------------------------------------------------
    def run_one(self, job):
        self._current = job
        self._preempt_sent = False
        self._cancelled = False
        self._requeue_devices = None
        self._requeue_reason = None
        # one span per ATTEMPT, parented on the service root span:
        # job_started/job_done/job_requeued of this attempt share it,
        # and the engine-run segments parent onto it via trace_scope
        if getattr(job, "trace_id", None):
            self._spans[job.job_id] = new_span_id()
        try:
            if self._breaker_blocks(job):
                return None
            if job.kind == "shell":
                return self._run_shell(job)
            if job.kind == "sim":
                return self._run_sim(job)
            if job.kind == "validate":
                if _is_light(job):
                    return self._run_validate_interp(job)
                return self._run_validate(job)
            if _is_light(job):
                return self._run_lint_only(job)
            return self._run_check(job)
        finally:
            self._release_hold(job.job_id)
            self.pool.release(job.job_id)
            self._current = None
            self._specs.pop(job.job_id, None)
            self._spans.pop(job.job_id, None)

    def run_one_light(self, job):
        """Run one LIGHT job (shell / interp validate / lint-only) —
        the multi-runner's thread entry.  Touches none of the per-job
        preemption fields ``run_one`` owns, so it is safe beside a
        concurrently running mesh job; any unexpected error fails the
        JOB, never the thread pool."""
        from .queue import FencedError, QueueError
        if getattr(job, "trace_id", None):
            self._spans[job.job_id] = new_span_id()
        try:
            if self._breaker_blocks(job):
                return
            if job.kind == "shell":
                self._run_shell(job)
            elif job.kind == "validate":
                self._run_validate_interp(job)
            elif job.kind == "check" and job.flags.get("lint_only"):
                self._run_lint_only(job)
            else:
                self._finish(job, "failed",
                             reason="not-a-light-job (multi-runner "
                                    "dispatch bug)")
        except (QueueError, FencedError):
            pass                  # lost race against a sibling worker
        except Exception as e:  # noqa: BLE001 — a job, not the worker
            try:
                self._finish(job, "failed",
                             reason=f"light-runner: "
                                    f"{type(e).__name__}: {e}")
            except (QueueError, FencedError):
                pass
        finally:
            self._release_hold(job.job_id)
            self.pool.release(job.job_id)
            self._specs.pop(job.job_id, None)
            self._spans.pop(job.job_id, None)

    # -- light jobs (the multi-runner lane, ISSUE 14) ------------------
    def _run_validate_interp(self, job):
        """``kind="validate"`` + ``flags.interp``: the interpreter
        reference validator (``tpuvsr/validate/host.py``) — pure
        Python, zero devices, safe on the multi-runner threads.  The
        full nondeterminism handling is identical to the batch
        engine's (the batch engine cross-checks against THIS path), so
        verdicts match the device run bit-for-bit."""
        from ..validate import host_validate_batch, load_traces
        from ..validate.batch import validate_result_summary
        spec = self._specs.get(job.job_id) or self._load_spec(job)
        self._journal(job, "job_started", attempt=job.attempts,
                      devices=0, backend="cpu",
                      placement="light: interpreter validator "
                                "(multi-runner)")
        try:
            traces_path = job.flags.get("traces")
            if not traces_path:
                raise ValueError("validate jobs need flags.traces "
                                 "(the TRACE.jsonl path)")
            traces = load_traces(traces_path, spec)
            res = host_validate_batch(spec, traces, log=self._log)
        except Exception as e:  # noqa: BLE001 — a job, not the worker
            self._finish(job, "failed",
                         reason=f"job-setup: {type(e).__name__}: {e}")
            return
        state = ("failed" if res.error
                 else "violated" if res.divergences else "done")
        self._finish(job, state, result=validate_result_summary(res),
                     reason=res.error)

    def _run_lint_only(self, job):
        """``kind="check"`` + ``flags.lint_only``: a speclint report
        job — the analyzer already gated admission, so by the time
        this runs the spec is clean; the "run" publishes the full
        report as the job result.  Zero devices, zero jax."""
        from ..analysis import run_lint
        spec = self._specs.get(job.job_id) or self._load_spec(job)
        self._journal(job, "job_started", attempt=job.attempts,
                      devices=0, backend="cpu",
                      placement="light: speclint report "
                                "(multi-runner)")
        try:
            report = run_lint(spec)
        except Exception as e:  # noqa: BLE001 — a job, not the worker
            self._finish(job, "failed",
                         reason=f"job-setup: {type(e).__name__}: {e}")
            return
        findings = [f"{f.passname}: {f.message}"
                    for f in (report.errors + report.warnings)]
        state = "failed" if report.exit_code else "done"
        self._finish(job, state,
                     result={"speclint": findings,
                             "errors": len(report.errors),
                             "warnings": len(report.warnings)},
                     reason="speclint" if report.exit_code else None)

    def _breaker_blocks(self, job):
        """Fail a job fast — reason ``"breaker-open"`` — when its
        (tenant, spec-digest) circuit breaker is open (ISSUE 18): a
        crash-looping spec must stop consuming device time after K
        failures.  The check runs BEFORE any scheduler allocation;
        the half-open probe after cooldown is the one run allowed
        through to test recovery."""
        if self.guard is None:
            return False
        from ..serve.guard import spec_digest
        digest = spec_digest(job.spec, job.cfg)
        if self.guard.breaker_allow(job.tenant, digest,
                                    ts=time.time()):
            return False
        self._finish(job, "failed", reason="breaker-open")
        return True

    def _finish(self, job, state, **kw):
        from .queue import FencedError
        try:
            self.queue.finish(job.job_id, state, **kw)
        except FencedError as e:
            # our claim was recovered (and possibly re-issued) while
            # we were presumed dead — the successor owns this job now.
            # Drop OUR outcome: committing it too would double-count
            # the job (the exactly-once story the fence exists for)
            self.log(f"job {job.job_id}: fenced, dropping {state} "
                     f"({e})")
            self.processed.append((job.job_id, "fenced"))
            return
        self._journal(job, "job_done", state=state,
                      reason=kw.get("reason"))
        self.processed.append((job.job_id, state))
        # feed the circuit breaker every REAL terminal outcome:
        # `failed` is a breaker failure, `done`/`violated` successes
        # (a counterexample is the engine working, not crashing);
        # breaker-open fast-fails must not re-count as failures or an
        # open breaker would feed itself
        if self.guard is not None and kw.get("reason") != "breaker-open" \
                and state in ("done", "violated", "failed"):
            from ..serve.guard import spec_digest
            self.guard.breaker_record(
                job.tenant, spec_digest(job.spec, job.cfg),
                state != "failed", ts=time.time())
        self.log(f"job {job.job_id}: {state}"
                 + (f" ({kw.get('reason')})" if kw.get("reason")
                    else ""))

    def _run_check(self, job):
        from ..resilience import faults
        from ..resilience.supervisor import run_supervised
        spec = self._specs.get(job.job_id) or self._load_spec(job)
        kind = job.engine if job.engine in ("device", "paged",
                                            "sharded") else "device"
        alloc = self.scheduler.alloc_for(job)
        self.pool.alloc(job.job_id, alloc)
        backend, why = advise_backend(job, tpu_devices=self.tpu_devices,
                                      bench_dir=self.bench_dir)
        self._journal(job, "job_started", attempt=job.attempts,
                      devices=alloc, backend=backend,
                      placement=why)
        flags = job.flags
        injected = None
        try:
            # everything from here to the outcome is THIS JOB's
            # problem: malformed flags (bad supervisor kwargs, a bad
            # -inject grammar) fail the job, never the worker
            factory = None
            if flags.get("stub"):
                from ..testing import stub_service_factory
                engine_kw = {}
                if flags.get("pipeline"):
                    engine_kw["pipeline"] = int(flags["pipeline"])
                factory = stub_service_factory(
                    spec, inv_bound=flags.get("inv_bound"),
                    inv_x_bound=flags.get("inv_x_bound"), **engine_kw)
            sup_kw = dict(flags.get("supervisor") or {})
            sup_kw.setdefault("backoff_base", 0.0)

            def observer_factory(**kw):
                return JobObserver(
                    tick=lambda depth: self._tick(job, depth), **kw)

            injected = flags.get("inject")
            if injected:
                faults.install(injected)
            # the engine's own journal (RunObserver) runs inside the
            # attempt span's trace scope, so every run_start /
            # level_done / fault / run_end of this attempt carries the
            # job's trace_id with a fresh per-segment span (ISSUE 17)
            with trace_scope(job.trace_id,
                             parent_span=self._spans.get(job.job_id)):
                out = run_supervised(
                    spec, engine=kind,
                    checkpoint_path=self.queue.checkpoint_path(
                        job.job_id),
                    journal_path=self.queue.journal_path(job.job_id),
                    metrics_path=self.queue.metrics_path(job.job_id),
                    log=self._log, engine_factory=factory,
                    observer_factory=observer_factory,
                    mesh_devices=(alloc if kind == "sharded" else None),
                    engine_kwargs=(
                        {"pipeline": int(flags["pipeline"])}
                        if flags.get("pipeline") and not factory
                        else None),
                    **sup_kw,
                    run_kwargs={
                        "max_states": flags.get("maxstates"),
                        "max_depth": flags.get("maxdepth"),
                        "max_seconds": flags.get("maxseconds"),
                        "check_deadlock": bool(flags.get("deadlock")),
                        "resume_from": (job.rescue or {}).get("path"),
                    })
        except Exception as e:  # noqa: BLE001 — a job, not the worker
            self._finish(job, "failed",
                         reason=f"job-setup: {type(e).__name__}: {e}")
            return
        finally:
            if injected:
                faults.clear()

        self._settle(job, out, result_summary)

    def _settle(self, job, out, summarize):
        """Map a run :class:`Outcome` onto the queue — shared by the
        check and sim paths."""
        if out.state == "preempted-requeued":
            if self._cancelled:
                self._finish(job, "cancelled", reason="cancelled",
                             result={"rescue": out.rescue})
                return
            reason = self._requeue_reason or \
                f"preempted ({(out.rescue or {}).get('signal')})"
            from .queue import FencedError
            try:
                self.queue.requeue(
                    job.job_id, reason=reason, rescue=out.rescue,
                    devices=self._requeue_devices)
            except FencedError as e:
                # recovered out from under us mid-run: the successor
                # already requeued (or re-ran) this job — drop ours
                self.log(f"job {job.job_id}: fenced, dropping "
                         f"requeue ({e})")
                self.processed.append((job.job_id, "fenced"))
                return
            self._journal(job, "job_requeued", reason=reason,
                          rescue=out.rescue,
                          devices=self._requeue_devices or job.devices)
            self.processed.append((job.job_id, "preempted-requeued"))
            self.log(f"job {job.job_id}: requeued ({reason})")
            # a REAL operator signal (not our scheduler/cancel tick,
            # not the job's own injected kill drill) means the whole
            # worker was asked to stop: requeue-and-exit, or the drain
            # loop would instantly re-claim the job and `serve` could
            # never be stopped gracefully
            sig = (out.rescue or {}).get("signal")
            simulated = "kill" in str(job.flags.get("inject") or "")
            if sig in ("SIGTERM", "SIGINT") and not self._preempt_sent \
                    and not simulated:
                self._shutdown = True
                self.log(f"{sig} received: job requeued; stopping the "
                         f"drain loop (rerun `serve` to resume)")
            return
        result = (summarize(out.result)
                  if out.result is not None else None)
        if result is not None:
            result["supervisor"] = out.summary
        self._finish(job, out.state, result=result, reason=out.error)

    # -- sim jobs (the fleet defect hunt, ISSUE 7) ---------------------
    def _run_sim(self, job):
        """``kind="sim"``: a walker-fleet defect hunt (tpuvsr/sim) run
        through ``run_hunt_job`` — the hunt twin of the supervised
        check path.  Fleet chunk boundaries tick the scheduler exactly
        like BFS level boundaries, so cancel and elastic shrink/grow
        ride the ordinary preempt-requeue machinery; the rescue is the
        walker-frontier snapshot and a resumed hunt replays
        bit-identically."""
        from ..resilience import faults
        from ..sim.hunt import run_hunt_job, sim_result_summary
        spec = self._specs.get(job.job_id) or self._load_spec(job)
        alloc = self.scheduler.alloc_for(job)
        self.pool.alloc(job.job_id, alloc)
        backend, why = advise_backend(job, tpu_devices=self.tpu_devices,
                                      bench_dir=self.bench_dir)
        self._journal(job, "job_started", attempt=job.attempts,
                      devices=alloc, backend=backend, placement=why)
        flags = job.flags
        injected = None
        try:
            factory = None
            if flags.get("stub"):
                from ..testing import stub_model_factory
                factory = stub_model_factory(
                    inv_bound=flags.get("inv_bound"),
                    inv_x_bound=flags.get("inv_x_bound"))
            split = flags.get("split")
            if isinstance(split, dict):
                from ..sim.splitting import NoveltySplitter
                split = NoveltySplitter(**split)
            else:
                split = True if split else None

            def observer_factory(**kw):
                return JobObserver(
                    tick=lambda depth: self._tick(job, depth), **kw)

            injected = flags.get("inject")
            if injected:
                faults.install(injected)
            # zero/negative values must fail the job, not silently
            # become the defaults (the CLI rejects -walkers 0 with
            # exit 2; the service matches by failing at setup)
            walkers = flags.get("walkers")
            walkers = 512 if walkers is None else int(walkers)
            if flags.get("walkers_per_device"):
                # walker-count elasticity: the fleet size follows the
                # device allocation (applied at round boundaries; a
                # mid-round resume finishes the round at the rescue's
                # count first — the determinism contract)
                walkers = max(1, int(flags["walkers_per_device"])
                              * alloc)
            depth = flags.get("depth")
            depth = 100 if depth is None else int(depth)
            num = flags.get("num")
            if num is None and not flags.get("maxseconds") \
                    and not flags.get("max_violations") \
                    and not flags.get("hunt"):
                # bounded default so an unparameterized job drains;
                # flags {"hunt": true} opts into the continuous mode
                # (runs until cancelled/preempted)
                num = 10000
            with trace_scope(job.trace_id,
                             parent_span=self._spans.get(job.job_id)):
                out = run_hunt_job(
                    spec,
                    checkpoint_path=self.queue.checkpoint_path(
                        job.job_id),
                    journal_path=self.queue.journal_path(job.job_id),
                    metrics_path=self.queue.metrics_path(job.job_id),
                    log=self._log, observer_factory=observer_factory,
                    model_factory=factory, walkers=walkers,
                    n_devices=alloc, depth=depth,
                    seed=int(flags.get("seed") or 0), num=num,
                    max_seconds=flags.get("maxseconds"),
                    max_violations=flags.get("max_violations"),
                    split=split,
                    chunk_steps=int(flags.get("chunk_steps") or 16),
                    pipeline=int(flags.get("pipeline") or 2),
                    resume_from=(job.rescue or {}).get("path"))
        except Exception as e:  # noqa: BLE001 — a job, not the worker
            self._finish(job, "failed",
                         reason=f"job-setup: {type(e).__name__}: {e}")
            return
        finally:
            if injected:
                faults.clear()
        self._settle(job, out, sim_result_summary)

    # -- validate jobs (batched trace validation, ISSUE 8) -------------
    def _run_validate(self, job):
        """``kind="validate"``: a recorded-trace batch checked against
        the spec through ``run_validate_job`` — the validation twin of
        the sim path.  ``flags.traces`` names the TRACE.jsonl file;
        speclint admission already ran at ``queued -> admitted`` (the
        shared gate), so no device time is spent on a rejected spec.
        Validate-chunk boundaries tick the scheduler exactly like BFS
        level boundaries, so cancel and elastic trace-batch placement
        ride the ordinary preempt-requeue machinery; the rescue is the
        CRC'd candidate-frontier snapshot and a resumed batch reports
        bit-identical divergences on whatever mesh the new allocation
        builds."""
        from ..resilience import faults
        from ..validate.batch import (run_validate_job,
                                      validate_result_summary)
        spec = self._specs.get(job.job_id) or self._load_spec(job)
        alloc = self.scheduler.alloc_for(job)
        self.pool.alloc(job.job_id, alloc)
        backend, why = advise_backend(job, tpu_devices=self.tpu_devices,
                                      bench_dir=self.bench_dir)
        self._journal(job, "job_started", attempt=job.attempts,
                      devices=alloc, backend=backend, placement=why)
        flags = job.flags
        injected = None
        try:
            factory = None
            if flags.get("stub"):
                from ..testing import stub_model_factory
                factory = stub_model_factory(
                    inv_bound=flags.get("inv_bound"),
                    inv_x_bound=flags.get("inv_x_bound"))
            traces_path = flags.get("traces")
            if not traces_path:
                raise ValueError("validate jobs need flags.traces "
                                 "(the TRACE.jsonl path)")
            from ..validate import load_traces
            traces = load_traces(traces_path, spec)

            def observer_factory(**kw):
                return JobObserver(
                    tick=lambda depth: self._tick(job, depth), **kw)

            injected = flags.get("inject")
            if injected:
                faults.install(injected)
            batch = flags.get("batch")
            batch = 1024 if batch is None else int(batch)
            if flags.get("batch_per_device"):
                # elastic trace-batch placement: the round size
                # follows the device allocation (a resume finishes
                # its round at the rescue's batch first — the
                # determinism contract is per-trace, so reports are
                # unchanged either way)
                batch = max(1, int(flags["batch_per_device"]) * alloc)
            with trace_scope(job.trace_id,
                             parent_span=self._spans.get(job.job_id)):
                out = run_validate_job(
                    spec, traces,
                    checkpoint_path=self.queue.checkpoint_path(
                        job.job_id),
                    journal_path=self.queue.journal_path(job.job_id),
                    metrics_path=self.queue.metrics_path(job.job_id),
                    log=self._log, observer_factory=observer_factory,
                    model_factory=factory, batch=batch,
                    n_devices=alloc,
                    cand_cap=int(flags.get("cand_cap") or 4),
                    chunk_steps=int(flags.get("chunk_steps") or 8),
                    pipeline=int(flags.get("pipeline") or 2),
                    max_seconds=flags.get("maxseconds"),
                    resume_from=(job.rescue or {}).get("path"))
        except Exception as e:  # noqa: BLE001 — a job, not the worker
            self._finish(job, "failed",
                         reason=f"job-setup: {type(e).__name__}: {e}")
            return
        finally:
            if injected:
                faults.clear()
        self._settle(job, out, validate_result_summary)

    # -- shell jobs (the absorbed tpu_queue workload driver) -----------
    def _run_shell(self, job):
        flags = job.flags
        argv = flags.get("argv") or []
        timeout = float(flags.get("timeout") or 3600)
        env = dict(os.environ)
        env.update(flags.get("env") or {})
        # hand THIS job's trace context to the child (and scrub any
        # scope a sibling job exported on this process): a tpuvsr
        # child journals with the submitting job's trace_id
        for k in ("TPUVSR_TRACE_ID", "TPUVSR_SPAN_ID",
                  "TPUVSR_PARENT_SPAN"):
            env.pop(k, None)
        if getattr(job, "trace_id", None):
            env.update(trace_env(
                job.trace_id,
                parent_span=self._spans.get(job.job_id)))
        cwd = flags.get("cwd")
        # shell jobs are LIGHT (ISSUE 14): they spend their life in a
        # subprocess wait, so they hold a zero-device allocation and
        # never count against the mesh
        self._journal(job, "job_started", attempt=job.attempts,
                      devices=0)
        t0 = time.time()
        cancelled = False
        try:
            p = subprocess.Popen(argv, cwd=cwd, env=env,
                                 stdout=subprocess.PIPE,
                                 stderr=subprocess.STDOUT, text=True,
                                 start_new_session=True)
            # poll in short slices so a `cancel` lands mid-run (shell
            # jobs have no level boundaries — SIGTERM the process
            # group and let it exit; a well-behaved tpuvsr child
            # rescues and exits 75 on its own)
            rc = None
            while True:
                remaining = timeout - (time.time() - t0)
                try:
                    out, _ = p.communicate(
                        timeout=max(0.1, min(2.0, remaining)))
                    rc = p.returncode
                    break
                except subprocess.TimeoutExpired:
                    if remaining <= 0:
                        os.killpg(p.pid, signal.SIGKILL)
                        out, _ = p.communicate()
                        rc = -9
                        break
                    # the poll slice doubles as the heartbeat (shell
                    # jobs have no level boundaries to tick at)
                    self.queue.heartbeat(job.job_id)
                    self.queue.refresh()
                    if not cancelled and \
                            self.queue.cancel_requested(job.job_id):
                        cancelled = True
                        os.killpg(p.pid, signal.SIGTERM)
                        # one more slice to exit, then hard-kill
                        timeout = min(timeout,
                                      (time.time() - t0) + 10.0)
        except Exception as e:  # noqa: BLE001 — a job, not the worker
            rc, out = -1, f"launcher error: {e}"
        tail = "\n".join((out or "").strip().splitlines()[-6:])
        result = {"rc": rc, "tail": tail,
                  "elapsed_s": round(time.time() - t0, 1)}
        if cancelled:
            self._finish(job, "cancelled", reason="cancelled",
                         result=result)
            return
        state = job_state(rc) if rc >= 0 else "failed"
        if rc == EX_RESUMABLE:
            # resumable, but bounded: a child that exits 75 forever
            # without progressing must not hot-loop (the attempt
            # budget the absorbed tpu_queue enforced)
            if job.attempts < int(flags.get("max_attempts") or 1):
                self.queue.requeue(job.job_id, reason="exit-75",
                                   rescue=None)
                self._journal(job, "job_requeued", reason="exit-75")
                self.processed.append((job.job_id,
                                       "preempted-requeued"))
                return
            self._finish(job, "failed", result=result,
                         reason=f"exit-75 after {job.attempts} "
                                f"attempts (budget exhausted)")
            return
        if state == "failed" and self.shell_retry_gate is not None \
                and self.shell_retry_gate(job, rc):
            # the failure never really ran (e.g. a tunnel flap):
            # refund the attempt and requeue
            self.queue.requeue(job.job_id, reason="retry-uncounted",
                               uncount=True)
            self._journal(job, "job_requeued", reason="retry-uncounted")
            self.processed.append((job.job_id, "preempted-requeued"))
            return
        if state == "failed" and job.attempts < int(
                flags.get("max_attempts") or 1):
            self.queue.requeue(job.job_id, reason=f"retry rc={rc}")
            self._journal(job, "job_requeued", reason=f"retry rc={rc}")
            self.processed.append((job.job_id, "preempted-requeued"))
            return
        self._finish(job, state, result=result,
                     reason=None if state == "done" else f"rc={rc}")

    # -- the drain loop ------------------------------------------------
    def drain(self, *, max_jobs=None, max_seconds=None,
              idle_exit=True):
        """Process jobs until the queue has nothing claimable (or the
        bounds hit).  Returns the number of job runs executed.

        With the multi-runner enabled, LIGHT jobs (shell /
        interp-validate / lint-only) are handed to the thread-pool
        side lane and the loop immediately claims again, so one worker
        keeps its mesh busy while light jobs drain beside it; the loop
        never exits while a light job is still in flight (its claim
        must settle)."""
        from .queue import CLAIMABLE
        t0 = time.time()
        runs = 0
        try:
            while True:
                if max_jobs is not None and runs >= max_jobs:
                    break
                if max_seconds is not None \
                        and time.time() - t0 >= max_seconds:
                    break
                self.queue.recover_stale(log=self._log)
                self.admit_pending()
                # evict cached specs of jobs this worker will never
                # run (cancelled before claim, drained by another
                # worker) — the cache must not grow with the spool's
                # history
                for jid in list(self._specs):
                    j = self.queue._jobs.get(jid)
                    if j is None or j.state not in (
                            "admitted", "preempted-requeued",
                            "running"):
                        self._specs.pop(jid, None)
                base_order = (self.policy.order if self.policy
                              else (lambda jobs: sorted(
                                  jobs,
                                  key=lambda j: (-j.priority, j.seq))))
                order = base_order
                if self.multirunner is not None and \
                        self.multirunner.inflight() >= \
                        self.multirunner.threads:
                    # light lane saturated: skip light jobs so they
                    # stay claimable for pool siblings instead of
                    # queueing (un-started but claimed) behind OUR
                    # two threads
                    def order(jobs, _base=base_order):
                        return [j for j in _base(jobs)
                                if not _is_light(j)]
                job = self.queue.claim_next(owner=self.owner,
                                            order=order)
                if job is None:
                    if self.multirunner is not None \
                            and self.multirunner.inflight():
                        time.sleep(self.poll)
                        continue
                    if idle_exit:
                        break
                    time.sleep(self.poll)
                    continue
                self._hold(job.job_id)
                if self.policy is not None:
                    # charge the fair-share ledger for the REAL claim
                    # and journal why this job won the pop (the
                    # sched_decision audit trail, SCHEMA.md)
                    waiting = [j for j in self.queue.jobs()
                               if j.state in CLAIMABLE]
                    self.policy.charge(job, waiting)
                    self._journal(job, "sched_decision",
                                  worker=self.owner,
                                  **self.policy.explain(job))
                runs += 1
                if self.multirunner is not None and _is_light(job):
                    self.multirunner.submit(job)
                    continue
                self.run_one(job)
                if self._shutdown:
                    break
        finally:
            if self.multirunner is not None:
                self.multirunner.close()
            self._hb_stop.set()
            if self._hb_thread is not None:
                self._hb_thread.join(5)
                self._hb_thread = None
        return runs
