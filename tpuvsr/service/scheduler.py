"""Mesh scheduler + elastic placement for the dispatch service.

Three concerns (ISSUE 6 tentpole, ROADMAP item 3):

* **Device ledger** (:class:`DevicePool`) — how many accelerator
  devices exist and which job holds how many.  The worker allocates at
  claim time and releases at job end; sharded jobs' allocations ARE
  their mesh sizes.
* **Packing + elasticity** (:class:`Scheduler`) — greedy bin-pack by
  requested device count (priority first, then submission order —
  exactly the order ``JobQueue.claim_next`` pops), plus the two LIVE
  reshape rules evaluated at every level boundary of a running job
  (the worker's observer tick):

  - *shrink / yield*: a higher-priority job arrived.  The running job
    is preempted through the ordinary rescue-checkpoint path
    (``request_preemption`` — the engines poll the same flag SIGTERM
    sets), and, when the arrival does not fit beside it, an elastic
    sharded job is requeued with a SMALLER mesh so both eventually
    pack.  The resume re-hash-partitions the snapshot onto the new
    mesh (PR 5 reshard-on-load) — nothing is lost but the in-flight
    level.
  - *grow*: a previously-shrunken elastic job is running below its
    requested device count and devices have freed up.  Preempt-to-grow
    requeues it with the bigger mesh; the elastic resume grows the
    same way it shrank.

* **Cross-backend placement advisory** (:func:`advise_backend`) — the
  cpu-vs-tpu call, using the same logic ``scripts/compare_bench.py``
  applies across backends: measured ``distinct_per_s`` from the
  newest usable bench documents decides, and tiny jobs stay on CPU
  (device compile time dominates them).  Advisory because every tier-1
  environment is CPU-only; the decision is recorded on the job's
  ``job_started`` event either way.

``watch_backend`` absorbs ``scripts/tpu_watch.py``: the probe loop
that audits tunnel availability is just the scheduler's
backend-availability input running detached.
"""

from __future__ import annotations

import glob
import json
import os
import time
from dataclasses import dataclass

from .queue import CLAIMABLE


def pow2_floor(n):
    """Largest power of two <= n (n >= 1)."""
    n = int(n)
    if n < 1:
        return 1
    p = 1
    while p * 2 <= n:
        p *= 2
    return p


def _clamp(n, lo, hi):
    return max(lo, min(hi, n))


class DevicePool:
    """Slot ledger over `total` devices.  Allocation is bookkeeping —
    jax device selection happens in the worker (`jax.devices()[:n]`) —
    but the ledger is what packing decisions read."""

    def __init__(self, total):
        self.total = int(total)
        self._alloc = {}

    @property
    def free(self):
        return self.total - sum(self._alloc.values())

    def held(self, job_id):
        return self._alloc.get(job_id, 0)

    def alloc(self, job_id, n):
        self._alloc[job_id] = int(n)

    def release(self, job_id):
        self._alloc.pop(job_id, None)

    def snapshot(self):
        return {"total": self.total, "free": self.free,
                "alloc": dict(self._alloc)}


@dataclass
class Decision:
    """One live-reshape decision for the currently running job."""
    action: str          # "shrink" | "grow" | "yield"
    devices: int         # the job's NEXT mesh size (requeue devices)
    reason: str


class Scheduler:
    def __init__(self, pool, elastic_grow=True, policy=None):
        self.pool = pool
        self.elastic_grow = elastic_grow
        #: fair-share policy (tpuvsr/serve/fairshare.py) — when set,
        #: every priority comparison below uses AGED priorities, so a
        #: long-waiting low-priority job eventually wins preemption
        #: decisions too, not just pop order (ISSUE 14)
        self.policy = policy

    def _prio(self, job):
        if self.policy is not None:
            return self.policy.effective_priority(job)
        return job.priority

    # -- claim-time placement -----------------------------------------
    def alloc_for(self, job):
        """Device count for a job being claimed: its current
        ``devices`` (the scheduler rewrites that field on elastic
        requeues), clamped to the pool and — for sharded jobs — to a
        power of two (the mesh-shrink contract)."""
        n = _clamp(int(job.devices or 1), 1, self.pool.total)
        if job.engine == "sharded":
            n = pow2_floor(n)
        return n

    def _bounds(self, job):
        lo = int(job.devices_min or 1)
        # the grow ceiling must NOT read job.devices — the scheduler
        # itself rewrites that on a shrink requeue; the preserved
        # original request is the fallback ceiling
        hi = int(job.devices_max
                 or job.flags.get("devices_requested")
                 or job.devices or 1)
        return max(1, lo), _clamp(hi, 1, self.pool.total)

    # -- level-boundary reshape ---------------------------------------
    def rebalance(self, running, jobs):
        """The live grow/shrink call, evaluated at a running job's
        level boundaries.  Returns a :class:`Decision` (the worker
        preempts and requeues with ``decision.devices``) or None.

        Shrink/yield: the highest-priority CLAIMABLE job outranking
        `running` preempts it; if that job cannot fit beside the
        current allocation, an elastic victim also gives up devices —
        down to the largest power of two that leaves room, floored at
        ``devices_min``.  Grow: an elastic job running BELOW its
        requested mesh (an earlier shrink) reclaims freed devices up
        to ``devices_max``."""
        cur = self.pool.held(running.job_id) or running.devices or 1
        waiting = sorted(
            (j for j in jobs
             if j.state in CLAIMABLE and j.job_id != running.job_id),
            key=lambda j: (-self._prio(j), j.seq))
        for j in waiting:
            if self._prio(j) <= self._prio(running):
                break
            new = cur
            if j.devices > self.pool.total - cur and running.elastic:
                lo, hi = self._bounds(running)
                room = max(1, self.pool.total - j.devices)
                # the power-of-two clamp is the SHARDED mesh-shrink
                # contract; a walker fleet (kind="sim") runs on any
                # device count — don't strand devices it could use
                if running.engine == "sharded":
                    room = pow2_floor(room)
                new = _clamp(room, lo, hi)
            if new < cur:
                return Decision("shrink", new,
                                f"make room for {j.job_id} "
                                f"(priority {self._prio(j)})")
            return Decision("yield", cur,
                            f"yield to {j.job_id} "
                            f"(priority {self._prio(j)})")
        if self.elastic_grow and running.elastic:
            lo, hi = self._bounds(running)
            requested = int(running.flags.get("devices_requested")
                            or running.devices or 1)
            # reserve capacity for everything still waiting at >= our
            # priority before taking the rest of the pool
            reserved = sum(j.devices for j in waiting
                           if self._prio(j) >= self._prio(running))
            room = max(1, self.pool.total - reserved)
            if running.engine == "sharded":
                room = pow2_floor(room)
            target = _clamp(room, lo, hi)
            if cur < requested and target > cur:
                return Decision("grow", target,
                                f"devices freed up ({cur} -> {target})")
        return None

    # -- queue-level packing view -------------------------------------
    def plan(self, jobs):
        """Greedy bin-pack preview for ``status``: which claimable
        jobs fit the free pool right now, in pop order."""
        free = self.pool.free
        placed, waiting = [], []
        for j in sorted((j for j in jobs if j.state in CLAIMABLE),
                        key=lambda j: (-self._prio(j), j.seq)):
            need = self.alloc_for(j)
            if need <= free:
                placed.append((j.job_id, need))
                free -= need
            else:
                waiting.append((j.job_id, need))
        return {"placed": placed, "waiting": waiting, "free": free}


# ---------------------------------------------------------------------
# cross-backend placement advisory (compare_bench logic)
# ---------------------------------------------------------------------

#: below this many states a run is compile-dominated on an accelerator
SMALL_JOB_STATES = 50_000

#: CAPACITY.md tier math: ~16 GB of HBM holds ~8e8 fingerprint slots
#: (16 B/state, the device_bfs scale note) — the per-device distinct-
#: state capacity the admission gate prices a requested tier at.
#: Jobs carrying an explicit ``flags.tier_states`` override it.
TIER_STATES_PER_DEVICE = 800_000_000


def tier_states_for(job):
    """Distinct-state capacity of the tier a job requested:
    ``flags.tier_states`` when explicit, else requested devices x the
    CAPACITY.md per-device FPSet price.  The bounds-pass admission
    gate (worker._admit_one, ISSUE 13) rejects jobs whose static
    ``state_bound`` provably exceeds it — before any device time."""
    t = job.flags.get("tier_states")
    if t is not None:
        return int(t)
    return max(1, int(job.devices or 1)) * TIER_STATES_PER_DEVICE


def _doc_throughput(doc):
    """distinct_per_s of one bench/metrics document — the same lookup
    order ``scripts/compare_bench.py`` uses (gauges.distinct_per_s,
    then distinct/elapsed, then the legacy bench ``value``).  The
    repo's BENCH_r*.json files wrap the bench RESULT line under a
    ``parsed`` key ({n, cmd, rc, tail, parsed}); unwrap it first."""
    if not isinstance(doc, dict):
        return None
    if isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]
    m = doc if doc.get("schema") == "tpuvsr-metrics/1" else None
    if m is None and isinstance(doc.get("metrics"), dict) \
            and doc["metrics"].get("schema") == "tpuvsr-metrics/1":
        m = doc["metrics"]
    if m is not None:
        g = m.get("gauges", {})
        if "distinct_per_s" in g:
            return float(g["distinct_per_s"])
        if m.get("elapsed_s") and m.get("distinct") is not None:
            return float(m["distinct"]) / float(m["elapsed_s"])
    if "value" in doc:
        try:
            return float(doc["value"])
        except (TypeError, ValueError):
            return None
    return None


def bench_throughputs(bench_dir):
    """Newest usable per-backend distinct/s from the repo's BENCH_r*
    documents: ``{"cpu": x, "tpu": y}`` (either may be absent)."""
    out = {}
    for path in sorted(glob.glob(os.path.join(bench_dir,
                                              "BENCH_r*.json"))):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        tp = _doc_throughput(doc)
        if tp is None:
            continue
        if isinstance(doc.get("parsed"), dict):
            doc = doc["parsed"]
        backend = str(doc.get("backend", "")).lower()
        key = "tpu" if "tpu" in backend and "fallback" not in backend \
            else "cpu"
        out[key] = tp              # sorted order: newest round wins
    return out


def advise_backend(job, *, tpu_devices=0, bench_dir=None):
    """cpu-vs-tpu placement for one job: ``(backend, reason)``.

    TPU only when it is actually reachable AND the job is big enough
    to amortize device compile AND the measured cross-backend
    throughput (newest bench documents, compare_bench semantics)
    favors it; cross-backend numbers are ADVISORY, like
    ``compare_bench`` treats them, so ties and missing data fall back
    to CPU."""
    if tpu_devices <= 0:
        return "cpu", "no tpu devices reachable"
    est = job.flags.get("maxstates") or job.flags.get("est_states")
    if est is not None and int(est) < SMALL_JOB_STATES:
        return "cpu", (f"small job ({est} states < "
                       f"{SMALL_JOB_STATES}): compile-dominated")
    if bench_dir is None:
        bench_dir = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
    tps = bench_throughputs(bench_dir)
    if "tpu" in tps and "cpu" in tps and tps["tpu"] > tps["cpu"]:
        return "tpu", (f"bench advisory: {tps['tpu']:.0f} vs "
                       f"{tps['cpu']:.0f} distinct/s")
    if "tpu" in tps and "cpu" not in tps:
        return "tpu", "bench advisory: only tpu rounds recorded"
    return "cpu", "bench advisory: no measured tpu advantage"


def detect_tpu_devices(flag_path=None):
    """TPU device count for the placement advisory, cheapest signal
    first: ``TPUVSR_TPU_DEVICES`` env, else the ``TPU_UP`` flag file
    the ``watch_backend`` loop maintains (its JSON line carries the
    probed device count).  0 when neither says the tunnel is up — no
    blocking probe here; `serve` must stay responsive."""
    env = os.environ.get("TPUVSR_TPU_DEVICES")
    if env:
        try:
            return max(0, int(env))
        except ValueError:
            pass
    if flag_path is None:
        repo = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        flag_path = os.path.join(repo, "scripts", "TPU_UP")
    try:
        with open(flag_path) as f:
            return max(0, int(json.load(f).get("devices", 0)))
    except (OSError, ValueError, TypeError):
        return 0


# ---------------------------------------------------------------------
# backend availability watch (absorbs scripts/tpu_watch.py)
# ---------------------------------------------------------------------
def watch_backend(log_path, flag_path, *, interval=300.0, timeout=75.0,
                  max_hours=13.0, probe=None, sleep=time.sleep,
                  clock=time.time):
    """Re-probe the TPU tunnel on a cadence for ``max_hours``,
    appending one JSON line per attempt to `log_path` and maintaining
    `flag_path` as an up/down flag file — the scheduler's
    backend-availability input, auditable after the fact.  `probe`
    defaults to ``tpuvsr.platform_select.probe_tpu``."""
    if probe is None:
        from ..platform_select import probe_tpu as probe
    t0 = clock()
    while clock() - t0 < max_hours * 3600:
        t = clock()
        n = probe(timeout)
        rec = {"ts": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                   time.gmtime(t)),
               "probe_s": round(clock() - t, 1), "devices": n}
        with open(log_path, "a") as f:
            f.write(json.dumps(rec) + "\n")
        if n > 0:
            with open(flag_path, "w") as f:
                f.write(json.dumps(rec) + "\n")
        elif os.path.exists(flag_path):
            os.remove(flag_path)
        sleep(max(0.0, interval - (clock() - t)))
