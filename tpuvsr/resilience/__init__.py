"""tpuvsr.resilience — survival machinery for long checking runs.

Three pieces (ISSUE 3 tentpole):

* **fault injection** (``faults.py``) — ``TPUVSR_FAULT`` / CLI
  ``-inject`` specs (``oom@level=3``, ``kill@level=5``,
  ``corrupt-ckpt:frontier.npz``, ``garble-ckpt:fpset.npz``,
  ``exchange-drop@shard=0``) fire
  deterministically inside the real engine loops and the checkpoint
  writer, so every recovery path below is tier-1-testable;
* **supervised run loop** (``supervisor.py``) — catches
  RESOURCE_EXHAUSTED, degrades (tile halving -> paged fallback; for
  ``engine="sharded"`` the mesh-aware ladder: tile -> mesh shrink to
  the largest pow2 device count -> paged, rank-agreed, with elastic
  snapshot resharding on resume — ISSUE 5) with bounded
  exponential-backoff retries resuming from the latest snapshot, and
  turns SIGTERM/SIGINT into checkpoint-at-next-level-boundary + the
  resumable exit code ``EXIT_RESUMABLE`` (75);
* **checkpoint hardening** lives in ``engine/checkpoint.py``
  (per-payload CRC32, fsync around the rename dance, ``.old``
  fallback on payload-level corruption) and is exercised through the
  ``corrupt-ckpt`` fault.

Every fault, retry, degrade and rescue checkpoint is journaled as a
``tpuvsr-journal/1`` event (``fault`` / ``retry`` / ``degrade`` /
``rescue_checkpoint`` — see ``tpuvsr/obs/SCHEMA.md``).
"""

from __future__ import annotations

from .faults import (FaultPlan, InjectedExchangeDrop, InjectedFault,
                     InjectedOOM, fault_point)
from .faults import clear as clear_faults
from .faults import install as install_faults
from .supervisor import (DEFAULT_MIN_TILE, EXIT_RESUMABLE, Outcome,
                         Preempted, PreemptionGuard, Supervisor,
                         clear_preemption, is_device_loss, is_oom,
                         preempt_signal, request_preemption,
                         run_supervised)

__all__ = [
    "FaultPlan", "InjectedFault", "InjectedOOM", "InjectedExchangeDrop",
    "fault_point", "install_faults", "clear_faults",
    "Supervisor", "PreemptionGuard", "Preempted", "EXIT_RESUMABLE",
    "DEFAULT_MIN_TILE", "is_oom", "is_device_loss", "preempt_signal",
    "request_preemption", "clear_preemption",
    "Outcome", "run_supervised",
]
