"""Deterministic fault injection for the checking engines (ISSUE 3).

The reference workload is a multi-day, 500 GB TLC run; the survival
machinery built around it (supervised retry/degrade, preemption-safe
checkpoints, payload-level ``.old`` fallback) is exactly the code that
never runs in a clean test.  This module makes every failure mode a
one-line spec that fires at a deterministic point inside the REAL
engine loops, so the recovery paths are tier-1-testable without a TPU,
a preemption, or a real out-of-memory.

Fault spec grammar (``TPUVSR_FAULT`` env var / CLI ``-inject``; entries
comma-separated, parameters attached with ``@key=value``):

    oom@level=3                    raise an injected RESOURCE_EXHAUSTED
                                   at the start of BFS level 3
    oom@shard=0                    same, scoped to one shard of a
                                   sharded run: fires only on HOST
                                   process 0 in multi-process runs (a
                                   single-process mesh drives every
                                   shard, so any armed shard fires) —
                                   the device-loss/per-shard-OOM drill
                                   the supervisor's mesh degrade
                                   ladder exists for
    kill@level=5                   SIGTERM this process at the start of
                                   level 5 (simulated preemption; with
                                   the supervisor's PreemptionGuard the
                                   run checkpoints at the next level
                                   boundary and exits resumable)
    corrupt-ckpt:frontier.npz      emulate a crash-corrupted snapshot
                                   write: the named payload of the next
                                   checkpoint is truncated and the
                                   previous snapshot is left as ``.old``
                                   (the crash window the fallback path
                                   exists for); ``@level=N`` pins it to
                                   the level-N snapshot
    garble-ckpt:fpset.npz          like corrupt-ckpt, but the payload
                                   is garbled IN PLACE (a byte span
                                   mid-file XOR-flipped, size
                                   preserved) — a torn/bit-rotted
                                   write only the manifest CRC32 can
                                   catch, exercising the CRC verify
                                   path directly (ISSUE 4 satellite)
    exchange-drop@shard=0          one transient exchange failure in the
                                   sharded engine (journaled, step
                                   re-issued); ``@level=N`` pins a
                                   level.  ``shard`` selects the HOST
                                   process in multi-process runs; a
                                   single-process mesh drives every
                                   shard, so any armed shard fires
    exchange-drop:3@shard=0        PERSISTENT flavor: the optional
                                   ``:K`` count makes the drop fire K
                                   consecutive times before clearing —
                                   the flaky-ICI-link drill the
                                   sharded driver's bounded
                                   exponential-backoff retry loop
                                   exists for (K greater than the
                                   retry budget exhausts it and the
                                   run fails loudly)

Each entry fires AT MOST ONCE (arm the same spec twice for a repeat;
``exchange-drop:K`` is the one counted exception — it fires K times).
Faults are journaled as ``fault`` events through the run's observer
before they act, so a journal always records *why* a run died or
degraded.  With no plan installed every hook is a cheap no-op.
"""

from __future__ import annotations

import os
import re
import signal

# fault kind -> the engine hook site it fires at
KIND_SITE = {
    "oom": "level",
    "kill": "level",
    "corrupt-ckpt": "checkpoint",
    "garble-ckpt": "checkpoint",
    "exchange-drop": "exchange",
}

# checkpoint-site kinds that need a payload file name
_CKPT_KINDS = ("corrupt-ckpt", "garble-ckpt")

_ENTRY_RE = re.compile(
    r"^(?P<kind>[a-z][a-z-]*)"
    r"(?::(?P<arg>[^@]+))?"
    r"(?P<params>(?:@[a-z]+=[\w.]+)*)$")


class InjectedFault(Exception):
    """Base class for deterministically injected faults."""


class InjectedOOM(InjectedFault):
    """Mimics an XLA allocation failure; the message carries
    RESOURCE_EXHAUSTED so ``supervisor.is_oom`` treats injected and
    real OOMs identically."""


class InjectedExchangeDrop(InjectedFault):
    """One transient sharded-exchange failure (the step is re-issued
    by the driver; the pause/re-enter protocol makes that safe)."""


class Fault:
    """One armed fault: kind + optional (level, shard, payload).

    ``count`` is the number of times the fault fires before it clears
    (1 for every kind except a counted ``exchange-drop:K``)."""

    __slots__ = ("kind", "site", "level", "shard", "payload", "fired",
                 "count")

    def __init__(self, kind, *, level=None, shard=None, payload=None,
                 count=1):
        if kind not in KIND_SITE:
            raise ValueError(
                f"unknown fault kind {kind!r} "
                f"(want one of {sorted(KIND_SITE)})")
        self.kind = kind
        self.site = KIND_SITE[kind]
        self.level = level
        self.shard = shard
        self.payload = payload
        self.count = int(count)
        if self.count < 1:
            raise ValueError(f"fault count must be >= 1 "
                             f"(got {count!r})")
        self.fired = False

    def matches(self, site, depth=None, shard=None):
        if self.fired or site != self.site:
            return False
        if self.level is not None and depth is not None \
                and depth != self.level:
            return False
        if self.level is not None and depth is None:
            return False
        if self.shard is not None and shard is not None \
                and shard != self.shard:
            return False
        return True

    def __repr__(self):
        parts = [self.kind]
        if self.payload:
            parts.append(f":{self.payload}")
        elif self.kind == "exchange-drop" and self.count != 1:
            parts.append(f":{self.count}")
        for k in ("level", "shard"):
            v = getattr(self, k)
            if v is not None:
                parts.append(f"@{k}={v}")
        return "".join(parts)


def parse_fault(entry):
    m = _ENTRY_RE.match(entry.strip())
    if not m:
        raise ValueError(f"unparsable fault spec {entry!r} "
                         f"(grammar: KIND[:ARG][@key=value ...])")
    kind = m.group("kind")
    kw = {}
    for p in re.findall(r"@([a-z]+)=([\w.]+)", m.group("params") or ""):
        key, val = p
        if key not in ("level", "shard"):
            raise ValueError(f"unknown fault parameter {key!r} "
                             f"in {entry!r} (want level/shard)")
        kw[key] = int(val)
    if m.group("arg"):
        if kind == "exchange-drop":
            # exchange-drop:K — the arg is a persistence count, not a
            # payload (a flaky link that drops K consecutive attempts)
            try:
                kw["count"] = int(m.group("arg"))
            except ValueError:
                raise ValueError(
                    f"{entry!r}: exchange-drop:K needs an integer "
                    f"count (got {m.group('arg')!r})")
        else:
            kw["payload"] = m.group("arg")
    if kind in _CKPT_KINDS and "payload" not in kw:
        raise ValueError(
            f"{entry!r}: {kind} needs a payload file name "
            f"(e.g. {kind}:frontier.npz)")
    return Fault(kind, **kw)


class FaultPlan:
    """An ordered set of one-shot faults; ``fire`` consumes the first
    match for a site."""

    def __init__(self, faults):
        self.faults = list(faults)

    @classmethod
    def parse(cls, text):
        entries = [e for e in re.split(r"[,;]", text or "") if e.strip()]
        return cls(parse_fault(e) for e in entries)

    def pending(self):
        return [f for f in self.faults if not f.fired]

    def fire(self, site, *, depth=None, shard=None, obs=None, path=None):
        """Fire the first unfired fault matching `site` (and the
        optional depth/shard context).  Journals the fault through
        `obs`, then acts:

        * ``oom``            raises InjectedOOM
        * ``kill``           SIGTERMs this process (a PreemptionGuard
                             turns that into checkpoint-and-exit; with
                             no handler installed the process dies —
                             raw preemption)
        * ``corrupt-ckpt``/``garble-ckpt``
                             returns the Fault itself; the caller (the
                             checkpoint writer) truncates or garbles
                             its ``payload`` per ``kind``
        * ``exchange-drop``  raises InjectedExchangeDrop

        Returns None when nothing fired."""
        for f in self.faults:
            if not f.matches(site, depth=depth, shard=shard):
                continue
            # counted faults (exchange-drop:K) clear after K fires;
            # everything else is one-shot
            f.count -= 1
            f.fired = f.count <= 0
            if obs is not None:
                extra = {}
                if depth is not None:
                    extra["depth"] = int(depth)
                if f.shard is not None:
                    extra["shard"] = int(f.shard)
                if f.payload is not None:
                    extra["payload"] = f.payload
                obs.fault(f.kind, site, **extra)
            if f.kind == "oom":
                raise InjectedOOM(
                    f"RESOURCE_EXHAUSTED: injected out-of-memory at "
                    f"level {depth} (fault {f!r})")
            if f.kind == "kill":
                os.kill(os.getpid(), signal.SIGTERM)
                return f.kind
            if f.kind in _CKPT_KINDS:
                return f
            if f.kind == "exchange-drop":
                raise InjectedExchangeDrop(
                    f"injected exchange drop at level {depth} "
                    f"(fault {f!r})")
        return None


# ---------------------------------------------------------------------
# process-wide plan (engines call the module-level hook; tests and the
# CLI -inject flag install a plan, TPUVSR_FAULT arms one lazily)
# ---------------------------------------------------------------------
_PLAN = None
_ENV_ARMED = False


def install(spec_or_plan):
    """Install a fault plan for this process (a spec string or a
    FaultPlan).  Returns the plan."""
    global _PLAN, _ENV_ARMED
    _PLAN = (spec_or_plan if isinstance(spec_or_plan, FaultPlan)
             else FaultPlan.parse(spec_or_plan))
    _ENV_ARMED = True          # an explicit plan overrides the env var
    return _PLAN


def clear():
    global _PLAN, _ENV_ARMED
    _PLAN = None
    _ENV_ARMED = False


def active():
    """The installed plan, arming one from TPUVSR_FAULT on first use."""
    global _PLAN, _ENV_ARMED
    if _PLAN is None and not _ENV_ARMED:
        env = os.environ.get("TPUVSR_FAULT")
        if env:
            _PLAN = FaultPlan.parse(env)
        _ENV_ARMED = True      # parse the env var once per process
    return _PLAN


def fault_point(site, *, depth=None, shard=None, obs=None, path=None):
    """Engine hook: no-op unless a plan with a matching unfired fault
    is armed (see FaultPlan.fire for the per-kind behavior)."""
    plan = active()
    if plan is None:
        return None
    return plan.fire(site, depth=depth, shard=shard, obs=obs, path=path)
