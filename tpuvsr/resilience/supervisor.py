"""Supervised run loop: retry/degrade on OOM, preemption-safe exits.

The survival machinery for multi-day checking runs (ISSUE 3 tentpole):

* **OOM retry/degrade** — ``Supervisor.run`` catches XLA
  ``RESOURCE_EXHAUSTED`` (and the injected ``faults.InjectedOOM``) and
  degrades instead of dying: halve the expansion tile and retry with
  exponential backoff (bounded attempts), resuming from the latest
  level-boundary snapshot; once the tile floor is reached, fall back
  from the HBM-resident device engine to the host-paged frontier
  (``hbm -> paged``).  Every step is journaled (``fault`` / ``retry`` /
  ``degrade`` events) so the journal shows *why* a run slowed.
* **Mesh-aware supervision** (ISSUE 5) — ``engine="sharded"`` runs the
  multi-chip engine through its own ladder: per-shard tile halving ->
  mesh shrink to the largest usable power-of-two device count (device
  loss skips straight to the shrink) -> single-device paged fallback
  (the sharded snapshot is converted in place so the final rung keeps
  the run's progress).  A shrunken-mesh resume re-hash-partitions the
  snapshot's N shards onto the smaller mesh
  (``ShardedBFS`` reshard-on-load, journaled as a ``reshard`` event).
  Restart decisions are rank-agreed — rank 0's classification of the
  failure is broadcast so every process of a multi-host pack takes
  the same branch of the ladder.
* **Preemption** — ``PreemptionGuard`` installs SIGTERM/SIGINT
  handlers that request a checkpoint at the next level boundary; the
  engines write the rescue snapshot, journal a ``rescue_checkpoint``
  event, and raise ``Preempted``, which the CLI maps to the distinct
  resumable exit code ``EXIT_RESUMABLE`` (75, BSD EX_TEMPFAIL).  A
  second signal while a rescue is pending aborts immediately.
* **Resume contract** — exit code 75 means "a resumable snapshot
  exists at the checkpoint dir": rerun with ``-recover DIR`` (or let
  ``scripts/supervise.py`` loop on the exit code) to continue the run
  with cumulative elapsed and one continuous journal.

The guard's pending flag is module state checked by the engines at
level boundaries (``preempt_signal()``); without a guard installed the
flag is never set and the checks are free.
"""

from __future__ import annotations

import os
import signal
import time
from dataclasses import dataclass, field

from ..exitcodes import (EX_OK, EX_RESUMABLE, EX_SOFTWARE, EX_VIOLATION,
                         job_state)
from ..obs import Journal, RunObserver
from .faults import InjectedFault, InjectedOOM

#: exit code of a preempted-but-resumable supervised run (EX_TEMPFAIL:
#: rerun with -recover to continue).  The value lives in the unified
#: exit-code table (tpuvsr/exitcodes.py, ISSUE 6 satellite); this name
#: is kept as the historical alias every caller imports.
EXIT_RESUMABLE = EX_RESUMABLE

#: smallest tile the degrade ladder will retry before falling back to
#: the paged engine
DEFAULT_MIN_TILE = 16


class Preempted(RuntimeError):
    """A run stopped at a level boundary because a PreemptionGuard
    caught SIGTERM/SIGINT; a resumable snapshot was written."""

    def __init__(self, path, depth, distinct, signal_name):
        self.path = path
        self.depth = int(depth)
        self.distinct = int(distinct)
        self.signal = signal_name
        where = (f"resumable snapshot at {path}" if path else
                 "NO snapshot was configured (-checkpoint/"
                 "-checkpointdir) — a restart re-explores from the "
                 "initial states")
        super().__init__(
            f"preempted by {signal_name} at level {depth} "
            f"({distinct} distinct); {where}")


# ---------------------------------------------------------------------
# preemption flag (module state; engines poll at level boundaries)
# ---------------------------------------------------------------------
_PENDING = [None]


def preempt_signal():
    """Name of the pending preemption signal, or None."""
    return _PENDING[0]


def request_preemption(name="SIGTERM"):
    _PENDING[0] = name


def clear_preemption():
    _PENDING[0] = None


class PreemptionGuard:
    """Context manager: SIGTERM/SIGINT -> checkpoint at the next level
    boundary and exit resumable, instead of dying mid-level.  A second
    signal while one is pending escalates to an immediate
    KeyboardInterrupt (impatient-operator escape hatch).  Installing
    handlers outside the main thread is a documented no-op."""

    SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self, log=None):
        self._log = log
        self._old = {}

    def _handler(self, signum, frame):
        name = signal.Signals(signum).name
        if preempt_signal() is not None:
            raise KeyboardInterrupt(
                f"second {name} while a rescue checkpoint was pending")
        request_preemption(name)
        if self._log:
            self._log(f"{name} received: checkpointing at the next "
                      f"level boundary, then exiting resumable "
                      f"(exit {EXIT_RESUMABLE})")

    def __enter__(self):
        clear_preemption()
        for sig in self.SIGNALS:
            try:
                self._old[sig] = signal.signal(sig, self._handler)
            except ValueError:      # not the main thread
                break
        return self

    def __exit__(self, exc_type, exc, tb):
        for sig, old in self._old.items():
            signal.signal(sig, old)
        self._old = {}
        clear_preemption()
        return False


# ---------------------------------------------------------------------
# OOM classification
# ---------------------------------------------------------------------
def is_oom(exc):
    """True for allocation-failure exceptions worth a degrade/retry:
    the injected OOM, XLA RESOURCE_EXHAUSTED, or a host MemoryError."""
    if isinstance(exc, (InjectedOOM, MemoryError)):
        return True
    msg = str(exc)
    return "RESOURCE_EXHAUSTED" in msg or "Out of memory" in msg \
        or "out of memory" in msg


def is_device_loss(exc):
    """True for failures that look like a device dropping out of the
    mesh (ICI/DCN link loss, halted chip, dead runtime client) — the
    pod-scale failure the sharded ladder answers with a mesh shrink
    rather than a tile halving (less tile would not bring the device
    back)."""
    msg = str(exc)
    return any(s in msg for s in (
        "DATA_LOSS", "device is in an invalid state",
        "Device or resource busy", "failed to connect",
        "Socket closed", "DEADLINE_EXCEEDED", "device halted",
        "UNAVAILABLE"))


def _pow2_below(n):
    """Largest power of two strictly below n (n >= 2)."""
    p = 1
    while p * 2 < n:
        p *= 2
    return p


class Supervisor:
    """Run a BFS engine to completion through the retry/degrade ladder.

    ``engine_factory(kind, tile_size)`` builds a fresh engine per
    attempt (kind is ``"device"``, ``"paged"`` or ``"sharded"``; a
    factory that also accepts an ``n_devices`` keyword is handed the
    current mesh size); the default factory builds
    DeviceBFS/PagedBFS/ShardedBFS on the supervisor's spec with
    ``engine_kwargs``.  The ladder on OOM:

        device:  tile -> tile/2 -> ... -> min_tile -> paged -> retry
        sharded: tile -> ... -> min_tile -> mesh D -> largest pow2 < D
                 -> ... -> min_devices -> paged (snapshot converted
                 in place so the fallback keeps the run's progress);
                 device-loss failures skip straight to the mesh shrink

    with exponential backoff between attempts and auto-resume from the
    supervisor's checkpoint dir whenever a snapshot exists — a sharded
    resume on a shrunken mesh re-hash-partitions the snapshot
    (``ShardedBFS`` reshard-on-load).  Violations, deadlocks and
    non-retryable errors propagate unchanged; ``Preempted`` propagates
    for the caller to map to EXIT_RESUMABLE.  Every restart decision
    is rank-agreed (rank 0's verdict broadcast) so a multi-host pack
    never splits across ladder branches."""

    def __init__(self, spec, engine="device", *, checkpoint_path=None,
                 checkpoint_every=None, journal_path=None,
                 metrics_path=None, log=None, tile_size=128,
                 min_tile=DEFAULT_MIN_TILE, max_retries=6,
                 backoff_base=0.5, backoff_cap=30.0,
                 engine_kwargs=None, engine_factory=None, fused=False,
                 chained=False, mesh_devices=None, min_devices=1,
                 sleep=time.sleep, observer_factory=None,
                 on_event=None):
        if fused and chained:
            raise ValueError("fused and chained are mutually "
                             "exclusive supervision modes")
        if engine not in ("device", "paged", "sharded"):
            raise ValueError(f"Supervisor supervises the device/paged/"
                             f"sharded engines, not {engine!r}")
        self.spec = spec
        self.kind = engine
        # mesh size for the sharded ladder: starts at `mesh_devices`
        # (default: every visible device) and only ever shrinks —
        # to the largest usable power of two — down to `min_devices`
        if engine == "sharded":
            if mesh_devices is None:
                import jax
                mesh_devices = len(jax.devices())
            self.n_dev = int(mesh_devices)
        else:
            self.n_dev = None
        self.min_devices = max(1, int(min_devices))
        # fused=True: first attempt runs the fused fixpoint with its
        # dispatch bounded to a rescue quantum (run_fused checkpoint
        # mode, ISSUE 4 satellite); any retry that has a snapshot to
        # resume from continues through the chunked engine (the fused
        # pass has no resume path) — journaled as a mode degrade
        self.fused = bool(fused)
        self._fused_degraded = False
        # chained=True (ISSUE 10 satellite): first attempt runs the
        # cross-level chained window with its new level-boundary
        # rescue seam (run_chained checkpoint mode); any retry that
        # has a snapshot resumes through the chunked engine — the
        # chained pass has no resume path — journaled as a mode
        # degrade exactly like the fused one
        self.chained = bool(chained)
        self._chained_degraded = False
        self.checkpoint_path = checkpoint_path
        self.checkpoint_every = checkpoint_every
        self.journal_path = journal_path
        self.metrics_path = metrics_path
        self.tile = int(tile_size)
        self.min_tile = int(min_tile)
        self.max_retries = int(max_retries)
        self.backoff_base = float(backoff_base)
        self.backoff_cap = float(backoff_cap)
        self._engine_kwargs = dict(engine_kwargs or {})
        self._factory = engine_factory
        self._sleep = sleep
        self._log = log
        # per-job hooks (ISSUE 6): `observer_factory` builds the
        # per-attempt RunObserver (the dispatch service substitutes one
        # whose level_done ticks the scheduler); `on_event` mirrors
        # every supervisor journal write as on_event(event, fields) so
        # a host process can track degrades/retries without re-reading
        # the journal file
        self._observer_factory = observer_factory or RunObserver
        self._on_event = on_event
        self.engine = None          # last engine instance (CLI liveness)
        self.attempts = 0           # engine runs started
        self.degrades = []          # [(what, from, to), ...]
        self._skip_resume = False   # set when a snapshot became unusable
        self._journal = Journal(journal_path)
        self._t0 = time.time()

    def log(self, msg):
        if self._log:
            self._log(f"supervisor: {msg}")

    def _jwrite(self, event, **fields):
        self._journal.write(
            event, elapsed_s=round(time.time() - self._t0, 3), **fields)
        if self._on_event is not None:
            self._on_event(event, dict(fields))

    def _agree(self, flag):
        """Rank-agreed boolean: rank 0's verdict, broadcast, so every
        process of a multi-host pack takes the same ladder branch.
        Single-process: the flag itself."""
        import jax
        if jax.process_count() > 1:
            import numpy as np
            from jax.experimental import multihost_utils
            return bool(int(multihost_utils.broadcast_one_to_all(
                np.int32(bool(flag)))))
        return bool(flag)

    def _make_engine(self):
        if self._factory is not None:
            import inspect
            params = inspect.signature(self._factory).parameters
            if "n_devices" in params or any(
                    p.kind is inspect.Parameter.VAR_KEYWORD
                    for p in params.values()):
                return self._factory(self.kind, self.tile,
                                     n_devices=self.n_dev)
            return self._factory(self.kind, self.tile)
        if self.kind == "sharded":
            import numpy as np

            import jax
            from jax.sharding import Mesh

            from ..parallel.sharded_bfs import ShardedBFS
            kw = dict(self._engine_kwargs)
            kw["tile"] = self.tile
            mesh = Mesh(np.array(jax.devices()[:self.n_dev]), ("d",))
            return ShardedBFS(self.spec, mesh, **kw)
        from ..engine.device_bfs import DeviceBFS
        from ..engine.paged_bfs import PagedBFS
        kw = dict(self._engine_kwargs)
        kw["tile_size"] = self.tile
        cls = PagedBFS if self.kind == "paged" else DeviceBFS
        return cls(self.spec, **kw)

    def summary(self):
        return {"attempts": self.attempts, "engine": self.kind,
                "tile": self.tile, "fused": self.fused,
                "chained": self.chained,
                "mesh_devices": self.n_dev,
                "resharded_from": getattr(self.engine,
                                          "resharded_from", None),
                "degrades": [list(d) for d in self.degrades]}

    # ------------------------------------------------------------------
    def run(self, *, max_states=None, max_depth=None, max_seconds=None,
            check_deadlock=False, resume_from=None, **run_kwargs):
        resume = resume_from
        try:
            with PreemptionGuard(log=self._log):
                while True:
                    self.attempts += 1
                    self.engine = self._make_engine()
                    obs = self._observer_factory(
                        journal_path=self.journal_path,
                        metrics_path=self.metrics_path,
                        log=self._log)
                    use_fused = self.fused and self.kind == "device"
                    use_chained = self.chained and self.kind == "device"
                    if use_fused and resume is not None \
                            and not self._fused_degraded:
                        self._fused_degraded = True
                        self.degrades.append(("mode", "fused",
                                              "chunked"))
                        self._jwrite("degrade", what="mode",
                                     **{"from": "fused",
                                        "to": "chunked"})
                        self.log("resuming from a snapshot: the fused "
                                 "pass has no resume path; continuing "
                                 "through the chunked engine")
                    if use_chained and resume is not None \
                            and not self._chained_degraded:
                        self._chained_degraded = True
                        self.degrades.append(("mode", "chained",
                                              "chunked"))
                        self._jwrite("degrade", what="mode",
                                     **{"from": "chained",
                                        "to": "chunked"})
                        self.log("resuming from a snapshot: the "
                                 "chained window has no resume path; "
                                 "continuing through the chunked "
                                 "engine")
                    try:
                        if use_fused and resume is None:
                            return self.engine.run_fused(
                                max_states=max_states,
                                max_depth=max_depth,
                                max_seconds=max_seconds,
                                check_deadlock=check_deadlock,
                                checkpoint_path=self.checkpoint_path,
                                checkpoint_every=self.checkpoint_every,
                                obs=obs, log=self._log, **run_kwargs)
                        if use_chained and resume is None:
                            return self.engine.run_chained(
                                max_states=max_states,
                                max_depth=max_depth,
                                max_seconds=max_seconds,
                                check_deadlock=check_deadlock,
                                checkpoint_path=self.checkpoint_path,
                                checkpoint_every=self.checkpoint_every,
                                obs=obs, log=self._log, **run_kwargs)
                        return self.engine.run(
                            max_states=max_states, max_depth=max_depth,
                            max_seconds=max_seconds,
                            check_deadlock=check_deadlock,
                            checkpoint_path=self.checkpoint_path,
                            checkpoint_every=self.checkpoint_every,
                            resume_from=resume, obs=obs, log=self._log,
                            **run_kwargs)
                    except Preempted:
                        raise
                    except Exception as e:  # noqa: BLE001 — filtered below
                        # retryability is RANK-AGREED: rank 0's
                        # classification is broadcast so every process
                        # of a multi-host pack takes the same branch
                        # (a split here issues mismatched collectives)
                        retryable = is_oom(e) or (
                            self.kind == "sharded" and is_device_loss(e))
                        if not self._agree(retryable) \
                                or self.attempts > self.max_retries:
                            raise
                        self._handle_oom(e)
                        if self._skip_resume:
                            resume = None
                        elif self.checkpoint_path and \
                                os.path.isdir(self.checkpoint_path):
                            resume = self.checkpoint_path
                        # else: keep the caller's resume_from (the OOM
                        # hit before the first snapshot landed) — never
                        # silently abandon a snapshot we were asked to
                        # recover from
                        if resume is None:
                            self.log("no snapshot yet; restarting the "
                                     "run from the initial states")
        finally:
            self._journal.close()

    def _handle_oom(self, exc):
        # injected OOMs were journaled as `fault` events by the engine's
        # observer at fire time; journal real ones here so the journal
        # always explains the retry that follows
        self._skip_resume = False
        if not isinstance(exc, InjectedFault):
            self._jwrite("fault", what="oom", site="run")
        if self.kind == "sharded":
            self._degrade_sharded(exc)
            self._backoff_and_journal()
            return
        if self.kind != "paged" and self.tile // 2 >= self.min_tile:
            old, self.tile = self.tile, self.tile // 2
            self.degrades.append(("tile", old, self.tile))
            self._jwrite("degrade", what="tile",
                         **{"from": old, "to": self.tile})
            self.log(f"OOM ({exc}): degrading tile {old} -> {self.tile}")
        elif self.kind != "paged":
            self.degrades.append(("engine", "device", "paged"))
            self._jwrite("degrade", what="engine",
                         **{"from": "device", "to": "paged"})
            self.kind = "paged"
            self.log(f"OOM ({exc}): tile floor {self.min_tile} reached; "
                     f"falling back to the host-paged engine")
        else:
            self.log(f"OOM ({exc}): already on the paged engine; "
                     f"plain retry")
        self._backoff_and_journal()

    def _degrade_sharded(self, exc):
        """The mesh-aware ladder (ISSUE 5): per-shard tile halving ->
        mesh shrink to the largest usable power-of-two device count ->
        single-device paged fallback.  Device-loss failures skip the
        tile rung (a smaller tile does not bring a device back); the
        paged rung converts the sharded snapshot in place so the
        fallback resumes with the run's progress."""
        dev_lost = is_device_loss(exc) and not is_oom(exc)
        what = "device loss" if dev_lost else "OOM"
        if not dev_lost and self.tile // 2 >= self.min_tile:
            old, self.tile = self.tile, self.tile // 2
            self.degrades.append(("tile", old, self.tile))
            self._jwrite("degrade", what="tile",
                         **{"from": old, "to": self.tile})
            self.log(f"{what} ({exc}): degrading per-shard tile "
                     f"{old} -> {self.tile}")
        elif self.n_dev > max(1, self.min_devices):
            old = self.n_dev
            self.n_dev = max(self.min_devices, _pow2_below(self.n_dev))
            self.degrades.append(("mesh", old, self.n_dev))
            self._jwrite("degrade", what="mesh",
                         **{"from": old, "to": self.n_dev})
            self.log(f"{what} ({exc}): shrinking mesh {old} -> "
                     f"{self.n_dev} devices (resume re-hash-partitions "
                     f"the snapshot)")
        else:
            self.degrades.append(("engine", "sharded", "paged"))
            self._jwrite("degrade", what="engine",
                         **{"from": "sharded", "to": "paged"})
            self.kind = "paged"
            # sharded-only knobs (bucket_cap, axis, exchange_*, sleep,
            # check_deadlock, ...) never reach the paged constructor:
            # keep only what PagedBFS.__init__ actually accepts, so
            # the final ladder rung cannot die on a TypeError
            import inspect

            from ..engine.device_bfs import DeviceBFS
            from ..engine.paged_bfs import PagedBFS
            accepted = set()
            for cls in (DeviceBFS, PagedBFS):   # paged delegates *args
                for name, p in inspect.signature(
                        cls.__init__).parameters.items():
                    if p.kind not in (p.VAR_POSITIONAL, p.VAR_KEYWORD):
                        accepted.add(name)
            accepted.discard("self")
            for k in [k for k in self._engine_kwargs
                      if k not in accepted]:
                self._engine_kwargs.pop(k)
            self.log(f"{what} ({exc}): mesh floor reached; falling "
                     f"back to the single-device paged engine")
            if self.checkpoint_path and \
                    os.path.isdir(self.checkpoint_path):
                try:
                    from ..parallel.sharded_bfs import \
                        convert_sharded_snapshot
                    convert_sharded_snapshot(self.checkpoint_path,
                                             self.spec, log=self._log)
                except Exception as ce:  # noqa: BLE001 — keep degrading
                    self._skip_resume = True
                    self.log(f"sharded snapshot conversion failed "
                             f"({type(ce).__name__}: {ce}); the paged "
                             f"fallback restarts from the initial "
                             f"states")

    def _backoff_and_journal(self):
        from .backoff import backoff_delay
        backoff = backoff_delay(self.attempts, self.backoff_base,
                                self.backoff_cap)
        self._jwrite("retry", attempt=self.attempts,
                     backoff_s=round(backoff, 3))
        self.log(f"retry {self.attempts}/{self.max_retries} "
                 f"in {backoff:.1f}s")
        if backoff > 0:
            self._sleep(backoff)

    # ------------------------------------------------------------------
    # library mode (ISSUE 6 satellite): a worker process hosting MANY
    # jobs cannot let one preemption own the process exit — run() still
    # raises Preempted for the CLI (byte-identical behavior), while
    # run_to_outcome() folds every ending into an Outcome value.
    # ------------------------------------------------------------------
    def run_to_outcome(self, **run_kwargs) -> "Outcome":
        """``run()`` with every ending reified as an :class:`Outcome`
        instead of an exception/exit-code side channel:

        * clean fixpoint          -> ``done`` (EX_OK)
        * invariant/deadlock      -> ``violated`` (EX_VIOLATION)
        * ``Preempted``           -> ``preempted-requeued``
          (EX_RESUMABLE) with the rescue snapshot attached
        * anything non-retryable  -> ``failed`` (EX_SOFTWARE)

        The state strings ARE the service job terminal states — the
        mapping lives in ``tpuvsr.exitcodes.JOB_STATE`` and nowhere
        else."""
        try:
            res = self.run(**run_kwargs)
        except Preempted as p:
            return Outcome(
                state=job_state(EX_RESUMABLE), exit_code=EX_RESUMABLE,
                rescue={"path": p.path, "depth": p.depth,
                        "distinct": p.distinct, "signal": p.signal},
                summary=self.summary())
        except Exception as e:  # noqa: BLE001 — reified, not swallowed
            return Outcome(state=job_state(EX_SOFTWARE),
                           exit_code=EX_SOFTWARE,
                           error=f"{type(e).__name__}: {e}",
                           summary=self.summary())
        code = EX_OK if res.ok else EX_VIOLATION
        return Outcome(state=job_state(code), exit_code=code,
                       result=res, error=res.error,
                       summary=self.summary())


@dataclass
class Outcome:
    """The reified ending of a supervised run (library mode).

    ``state`` is a service job state (``done`` / ``violated`` /
    ``failed`` / ``preempted-requeued``) and ``exit_code`` the matching
    entry of the unified contract (tpuvsr/exitcodes.py) — the pair is
    always consistent by construction."""

    state: str
    exit_code: int
    result: object = None    # CheckResult when the run finished
    error: str = None
    rescue: dict = None      # {path, depth, distinct, signal} on preemption
    summary: dict = field(default_factory=dict)

    @property
    def resumable(self):
        return self.exit_code == EX_RESUMABLE


def run_supervised(spec, *, run_kwargs=None, **supervisor_kwargs):
    """One-call library entry: build a :class:`Supervisor` over `spec`
    and run it to an :class:`Outcome` — the worker-process twin of the
    CLI's ``-supervise`` path, returning instead of ``sys.exit``-ing so
    one process can host many jobs (tpuvsr/service/worker.py)."""
    sup = Supervisor(spec, **supervisor_kwargs)
    out = sup.run_to_outcome(**(run_kwargs or {}))
    out.supervisor = sup
    return out
