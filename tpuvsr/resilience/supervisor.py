"""Supervised run loop: retry/degrade on OOM, preemption-safe exits.

The survival machinery for multi-day checking runs (ISSUE 3 tentpole):

* **OOM retry/degrade** — ``Supervisor.run`` catches XLA
  ``RESOURCE_EXHAUSTED`` (and the injected ``faults.InjectedOOM``) and
  degrades instead of dying: halve the expansion tile and retry with
  exponential backoff (bounded attempts), resuming from the latest
  level-boundary snapshot; once the tile floor is reached, fall back
  from the HBM-resident device engine to the host-paged frontier
  (``hbm -> paged``).  Every step is journaled (``fault`` / ``retry`` /
  ``degrade`` events) so the journal shows *why* a run slowed.
* **Preemption** — ``PreemptionGuard`` installs SIGTERM/SIGINT
  handlers that request a checkpoint at the next level boundary; the
  engines write the rescue snapshot, journal a ``rescue_checkpoint``
  event, and raise ``Preempted``, which the CLI maps to the distinct
  resumable exit code ``EXIT_RESUMABLE`` (75, BSD EX_TEMPFAIL).  A
  second signal while a rescue is pending aborts immediately.
* **Resume contract** — exit code 75 means "a resumable snapshot
  exists at the checkpoint dir": rerun with ``-recover DIR`` (or let
  ``scripts/supervise.py`` loop on the exit code) to continue the run
  with cumulative elapsed and one continuous journal.

The guard's pending flag is module state checked by the engines at
level boundaries (``preempt_signal()``); without a guard installed the
flag is never set and the checks are free.
"""

from __future__ import annotations

import os
import signal
import time

from ..obs import Journal, RunObserver
from .faults import InjectedFault, InjectedOOM

#: exit code of a preempted-but-resumable supervised run (EX_TEMPFAIL:
#: rerun with -recover to continue).  Distinct from 0 (ok), 12 (TLC
#: safety violation), 1 (lint errors), 2 (bad flags).
EXIT_RESUMABLE = 75

#: smallest tile the degrade ladder will retry before falling back to
#: the paged engine
DEFAULT_MIN_TILE = 16


class Preempted(RuntimeError):
    """A run stopped at a level boundary because a PreemptionGuard
    caught SIGTERM/SIGINT; a resumable snapshot was written."""

    def __init__(self, path, depth, distinct, signal_name):
        self.path = path
        self.depth = int(depth)
        self.distinct = int(distinct)
        self.signal = signal_name
        where = (f"resumable snapshot at {path}" if path else
                 "NO snapshot was configured (-checkpoint/"
                 "-checkpointdir) — a restart re-explores from the "
                 "initial states")
        super().__init__(
            f"preempted by {signal_name} at level {depth} "
            f"({distinct} distinct); {where}")


# ---------------------------------------------------------------------
# preemption flag (module state; engines poll at level boundaries)
# ---------------------------------------------------------------------
_PENDING = [None]


def preempt_signal():
    """Name of the pending preemption signal, or None."""
    return _PENDING[0]


def request_preemption(name="SIGTERM"):
    _PENDING[0] = name


def clear_preemption():
    _PENDING[0] = None


class PreemptionGuard:
    """Context manager: SIGTERM/SIGINT -> checkpoint at the next level
    boundary and exit resumable, instead of dying mid-level.  A second
    signal while one is pending escalates to an immediate
    KeyboardInterrupt (impatient-operator escape hatch).  Installing
    handlers outside the main thread is a documented no-op."""

    SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self, log=None):
        self._log = log
        self._old = {}

    def _handler(self, signum, frame):
        name = signal.Signals(signum).name
        if preempt_signal() is not None:
            raise KeyboardInterrupt(
                f"second {name} while a rescue checkpoint was pending")
        request_preemption(name)
        if self._log:
            self._log(f"{name} received: checkpointing at the next "
                      f"level boundary, then exiting resumable "
                      f"(exit {EXIT_RESUMABLE})")

    def __enter__(self):
        clear_preemption()
        for sig in self.SIGNALS:
            try:
                self._old[sig] = signal.signal(sig, self._handler)
            except ValueError:      # not the main thread
                break
        return self

    def __exit__(self, exc_type, exc, tb):
        for sig, old in self._old.items():
            signal.signal(sig, old)
        self._old = {}
        clear_preemption()
        return False


# ---------------------------------------------------------------------
# OOM classification
# ---------------------------------------------------------------------
def is_oom(exc):
    """True for allocation-failure exceptions worth a degrade/retry:
    the injected OOM, XLA RESOURCE_EXHAUSTED, or a host MemoryError."""
    if isinstance(exc, (InjectedOOM, MemoryError)):
        return True
    msg = str(exc)
    return "RESOURCE_EXHAUSTED" in msg or "Out of memory" in msg \
        or "out of memory" in msg


class Supervisor:
    """Run a BFS engine to completion through the retry/degrade ladder.

    ``engine_factory(kind, tile_size)`` builds a fresh engine per
    attempt (kind is ``"device"`` or ``"paged"``); the default factory
    builds DeviceBFS/PagedBFS on the supervisor's spec with
    ``engine_kwargs``.  The ladder on OOM:

        tile -> tile/2 -> ... -> min_tile -> paged engine -> plain retry

    with exponential backoff between attempts and auto-resume from the
    supervisor's checkpoint dir whenever a snapshot exists.  Violations,
    deadlocks and non-OOM errors propagate unchanged; ``Preempted``
    propagates for the caller to map to EXIT_RESUMABLE."""

    def __init__(self, spec, engine="device", *, checkpoint_path=None,
                 checkpoint_every=None, journal_path=None,
                 metrics_path=None, log=None, tile_size=128,
                 min_tile=DEFAULT_MIN_TILE, max_retries=6,
                 backoff_base=0.5, backoff_cap=30.0,
                 engine_kwargs=None, engine_factory=None, fused=False,
                 sleep=time.sleep):
        if engine not in ("device", "paged"):
            raise ValueError(f"Supervisor supervises the device/paged "
                             f"engines, not {engine!r}")
        self.spec = spec
        self.kind = engine
        # fused=True: first attempt runs the fused fixpoint with its
        # dispatch bounded to a rescue quantum (run_fused checkpoint
        # mode, ISSUE 4 satellite); any retry that has a snapshot to
        # resume from continues through the chunked engine (the fused
        # pass has no resume path) — journaled as a mode degrade
        self.fused = bool(fused)
        self._fused_degraded = False
        self.checkpoint_path = checkpoint_path
        self.checkpoint_every = checkpoint_every
        self.journal_path = journal_path
        self.metrics_path = metrics_path
        self.tile = int(tile_size)
        self.min_tile = int(min_tile)
        self.max_retries = int(max_retries)
        self.backoff_base = float(backoff_base)
        self.backoff_cap = float(backoff_cap)
        self._engine_kwargs = dict(engine_kwargs or {})
        self._factory = engine_factory
        self._sleep = sleep
        self._log = log
        self.engine = None          # last engine instance (CLI liveness)
        self.attempts = 0           # engine runs started
        self.degrades = []          # [(what, from, to), ...]
        self._journal = Journal(journal_path)
        self._t0 = time.time()

    def log(self, msg):
        if self._log:
            self._log(f"supervisor: {msg}")

    def _jwrite(self, event, **fields):
        self._journal.write(
            event, elapsed_s=round(time.time() - self._t0, 3), **fields)

    def _make_engine(self):
        if self._factory is not None:
            return self._factory(self.kind, self.tile)
        from ..engine.device_bfs import DeviceBFS
        from ..engine.paged_bfs import PagedBFS
        kw = dict(self._engine_kwargs)
        kw["tile_size"] = self.tile
        cls = PagedBFS if self.kind == "paged" else DeviceBFS
        return cls(self.spec, **kw)

    def summary(self):
        return {"attempts": self.attempts, "engine": self.kind,
                "tile": self.tile, "fused": self.fused,
                "degrades": [list(d) for d in self.degrades]}

    # ------------------------------------------------------------------
    def run(self, *, max_states=None, max_depth=None, max_seconds=None,
            check_deadlock=False, resume_from=None, **run_kwargs):
        resume = resume_from
        try:
            with PreemptionGuard(log=self._log):
                while True:
                    self.attempts += 1
                    self.engine = self._make_engine()
                    obs = RunObserver(journal_path=self.journal_path,
                                      metrics_path=self.metrics_path,
                                      log=self._log)
                    use_fused = self.fused and self.kind == "device"
                    if use_fused and resume is not None \
                            and not self._fused_degraded:
                        self._fused_degraded = True
                        self.degrades.append(("mode", "fused",
                                              "chunked"))
                        self._jwrite("degrade", what="mode",
                                     **{"from": "fused",
                                        "to": "chunked"})
                        self.log("resuming from a snapshot: the fused "
                                 "pass has no resume path; continuing "
                                 "through the chunked engine")
                    try:
                        if use_fused and resume is None:
                            return self.engine.run_fused(
                                max_states=max_states,
                                max_depth=max_depth,
                                max_seconds=max_seconds,
                                check_deadlock=check_deadlock,
                                checkpoint_path=self.checkpoint_path,
                                checkpoint_every=self.checkpoint_every,
                                obs=obs, log=self._log, **run_kwargs)
                        return self.engine.run(
                            max_states=max_states, max_depth=max_depth,
                            max_seconds=max_seconds,
                            check_deadlock=check_deadlock,
                            checkpoint_path=self.checkpoint_path,
                            checkpoint_every=self.checkpoint_every,
                            resume_from=resume, obs=obs, log=self._log,
                            **run_kwargs)
                    except Preempted:
                        raise
                    except Exception as e:  # noqa: BLE001 — filtered below
                        if not is_oom(e) \
                                or self.attempts > self.max_retries:
                            raise
                        self._handle_oom(e)
                        if self.checkpoint_path and \
                                os.path.isdir(self.checkpoint_path):
                            resume = self.checkpoint_path
                        # else: keep the caller's resume_from (the OOM
                        # hit before the first snapshot landed) — never
                        # silently abandon a snapshot we were asked to
                        # recover from
                        if resume is None:
                            self.log("no snapshot yet; restarting the "
                                     "run from the initial states")
        finally:
            self._journal.close()

    def _handle_oom(self, exc):
        # injected OOMs were journaled as `fault` events by the engine's
        # observer at fire time; journal real ones here so the journal
        # always explains the retry that follows
        if not isinstance(exc, InjectedFault):
            self._jwrite("fault", what="oom", site="run")
        if self.kind != "paged" and self.tile // 2 >= self.min_tile:
            old, self.tile = self.tile, self.tile // 2
            self.degrades.append(("tile", old, self.tile))
            self._jwrite("degrade", what="tile",
                         **{"from": old, "to": self.tile})
            self.log(f"OOM ({exc}): degrading tile {old} -> {self.tile}")
        elif self.kind != "paged":
            self.degrades.append(("engine", "device", "paged"))
            self._jwrite("degrade", what="engine",
                         **{"from": "device", "to": "paged"})
            self.kind = "paged"
            self.log(f"OOM ({exc}): tile floor {self.min_tile} reached; "
                     f"falling back to the host-paged engine")
        else:
            self.log(f"OOM ({exc}): already on the paged engine; "
                     f"plain retry")
        backoff = min(self.backoff_cap,
                      self.backoff_base * (2 ** (self.attempts - 1)))
        self._jwrite("retry", attempt=self.attempts,
                     backoff_s=round(backoff, 3))
        self.log(f"retry {self.attempts}/{self.max_retries} "
                 f"in {backoff:.1f}s")
        if backoff > 0:
            self._sleep(backoff)
