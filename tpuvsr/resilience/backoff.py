"""The one bounded-exponential-backoff formula (ISSUE 18 satellite).

Four subsystems grew hand-rolled copies of the same curve — the
supervisor's retry ladder (``supervisor._backoff_and_journal``), the
sharded exchange retry loop (``parallel/sharded_bfs.py``), the worker
pool's dead-slot respawn (``serve/pool.py``) and now the circuit
breaker's re-open cooldown (``serve/guard.py``).  They all want the
same thing: attempt ``n`` (1-based) waits ``base * 2**(n-1)`` seconds,
capped.  One formula, one clamp discipline (negative bases and
attempts are floored, a zero cap means "no cap"), so the cooldown
vocabulary is shared and a test of the curve covers every caller.
"""

from __future__ import annotations


def backoff_delay(attempt, base, cap=None):
    """Seconds to wait before (1-based) retry ``attempt``:
    ``min(cap, base * 2**(attempt-1))``.

    ``attempt < 1`` is treated as the first attempt, a non-positive
    ``base`` waits nothing, and ``cap=None`` (or <= 0) leaves the
    curve unbounded — exactly the semantics of the four call sites
    this replaces.
    """
    base = max(0.0, float(base))
    n = max(1, int(attempt))
    # cap the EXPONENT too: 2**(n-1) overflows to inf-ish floats long
    # after the cap would have clamped it anyway
    delay = base * (2.0 ** min(n - 1, 63))
    if cap is not None and float(cap) > 0:
        delay = min(float(cap), delay)
    return delay


class BackoffSchedule:
    """A stateful view of the curve for callers that count their own
    attempts (the circuit breaker's re-open cooldown): ``next()``
    returns the delay for the next attempt and advances, ``reset()``
    rewinds to the first step."""

    def __init__(self, base, cap=None):
        self.base = float(base)
        self.cap = cap
        self.attempt = 0

    def next(self):
        self.attempt += 1
        return backoff_delay(self.attempt, self.base, self.cap)

    def peek(self):
        return backoff_delay(self.attempt + 1, self.base, self.cap)

    def reset(self):
        self.attempt = 0
