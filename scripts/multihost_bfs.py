"""DCN-tier demonstration: the sharded BFS engine running as a real
multi-process JAX job — 2 processes x 4 CPU devices federated with
jax.distributed over gloo/TCP (the DCN stand-in; on TPU pods the same
SPMD program spans hosts over actual DCN).  The flagship small config
(VSR R=3, |Values|=1, timer=1) is checked to fixpoint and the result
held to the pinned oracle (43,941 distinct / diameter 24 —
scripts/fixpoints.json).

Roles:
  python scripts/multihost_bfs.py            # launcher: spawns workers
  python scripts/multihost_bfs.py --worker   # one SPMD process

Env knobs (launcher): TPUVSR_MH_DEPTH (depth limit, 0 = fixpoint),
TPUVSR_MH_NPROCS (default 2), TPUVSR_MH_OUT (artifact path).
Writes scripts/multihost.json from rank 0.
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPTS = os.path.join(REPO, "scripts")
sys.path.insert(0, REPO)
sys.path.insert(0, SCRIPTS)

OUT = os.environ.get("TPUVSR_MH_OUT",
                     os.path.join(SCRIPTS, "multihost.json"))


def worker():
    from tpuvsr.parallel.multihost import init_from_env
    pid = init_from_env()
    import numpy as np
    import jax
    from jax.sharding import Mesh

    sys.argv = sys.argv[:1]
    from pin_fixpoints import load
    from tpuvsr.parallel.sharded_bfs import ShardedBFS

    spec = load("VSR", None, {"RestartEmptyLimit": "0"})
    mesh = Mesh(np.array(jax.devices()), ("d",))
    # bucket_cap None (occupancy-calibrated): the exchange wire volume
    # is cap-bound (D x D x cap rows per committed tile) and the gloo
    # loopback moved ~1.4 GB/tile at 4096 — the first full-fixpoint
    # attempt was wire-bound.  Buckets grow on overflow anyway.
    eng = ShardedBFS(spec, mesh, tile=64, bucket_cap=None,
                     next_capacity=1 << 14, fpset_capacity=1 << 16,
                     pipeline=int(os.environ.get(
                         "TPUVSR_MH_PIPELINE", "1")))
    depth = int(os.environ.get("TPUVSR_MH_DEPTH", "0")) or None
    log = (lambda m: print(f"[rank0] {m}", flush=True)) if pid == 0 \
        else None
    t0 = time.time()
    res = eng.run(max_depth=depth, log=log)
    if pid == 0:
        out = {
            "what": ("sharded BFS over a process-spanning mesh "
                     "(DCN tier): jax.distributed, gloo collectives"),
            "config": "VSR R=3, |Values|=1, timer=1",
            "processes": jax.process_count(),
            "local_devices": len(jax.local_devices()),
            "global_devices": len(jax.devices()),
            "ok": res.ok,
            "fixpoint": res.error is None,
            "distinct_states": res.distinct_states,
            "states_generated": res.states_generated,
            "diameter": res.diameter,
            "level_sizes": eng.level_sizes,
            "elapsed_s": round(time.time() - t0, 1),
            "distinct_per_s": round(res.distinct_states /
                                    max(res.elapsed, 1e-9), 1),
            "matches_pinned_43941": res.distinct_states == 43941
            if depth is None else None,
            "exchange": res.exchange,
        }
        with open(OUT, "w") as f:
            json.dump(out, f, indent=1)
        print(f"[rank0] wrote {OUT}: distinct={res.distinct_states} "
              f"diam={res.diameter} in {out['elapsed_s']}s", flush=True)


def launcher():
    from tpuvsr.parallel.multihost import launch
    nproc = int(os.environ.get("TPUVSR_MH_NPROCS", "2"))
    rcs, outs = launch(
        [sys.executable, os.path.abspath(__file__), "--worker"],
        nproc=nproc, local_devices=4,
        port=(int(os.environ["TPUVSR_MH_PORT"])
              if "TPUVSR_MH_PORT" in os.environ else None),
        timeout=float(os.environ.get("TPUVSR_MH_TIMEOUT", "2400")),
        extra_env={"TPUVSR_MH_DEPTH":
                   os.environ.get("TPUVSR_MH_DEPTH", "0"),
                   "TPUVSR_MH_OUT": OUT})
    for i, (rc, out) in enumerate(zip(rcs, outs)):
        tail = "\n".join(out.strip().splitlines()[-40:])
        print(f"--- worker {i} rc={rc}\n{tail}")
    if any(rc != 0 for rc in rcs):
        sys.exit(1)


if __name__ == "__main__":
    if "--worker" in sys.argv:
        worker()
    else:
        launcher()
