"""Measure the sharded exchange's wire/useful ratio with the
occupancy-calibrated bucket cap (VERDICT r4 item 7).

r4's DCN-tier run shipped 24x more bytes than it used (wire 3,216 MB
vs useful 134 MB, scripts/multihost.json) because the all_to_all moves
full D x bucket_cap buckets per tile and the cap was sized worst-case
(4096).  With bucket_cap=None the cap starts minimal and converges to
the observed high-water occupancy through the existing overflow-grow
pauses; this script runs the flagship small config on the virtual
8-device CPU mesh depth-limited and records both ratios.

Writes scripts/exchange_stats.json.

Usage: python scripts/exchange_stats.py [depth] [tile]
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
from tpuvsr.platform_select import force_cpu
force_cpu()

import numpy as np
import jax
from jax.sharding import Mesh

from __graft_entry__ import _small_spec
from tpuvsr.parallel.sharded_bfs import ShardedBFS

depth = int(sys.argv[1]) if len(sys.argv) > 1 else 12
tile = int(sys.argv[2]) if len(sys.argv) > 2 else 64

spec = _small_spec()
mesh = Mesh(np.array(jax.devices()[:8]), ("d",))
t0 = time.time()
eng = ShardedBFS(spec, mesh, tile=tile, bucket_cap=None,
                 next_capacity=1 << 14, fpset_capacity=1 << 16)
res = eng.run(max_depth=depth,
              log=lambda m: print(f"[exch] {m}", flush=True))
x = res.exchange
ratio = x["wire_bytes"] / max(1, x["useful_bytes"])
out = {
    "config": "VSR R=3, |Values|=1, timer=1 (flagship small)",
    "mesh": "8-device virtual CPU",
    "tile": tile,
    "depth": depth,
    "bucket_cap_final": eng.bucket_cap,
    "distinct_states": res.distinct_states,
    "level_sizes": eng.level_sizes,
    "elapsed_s": round(time.time() - t0, 1),
    "exchange": x,
    "wire_over_useful": round(ratio, 2),
    "r4_reference_wire_over_useful": 24.1,
    "meets_target_4x": ratio <= 4.0,
    "note": ("bucket_cap=None starts at max(64, tile) and converges "
             "via overflow-grow; wire volume is cap-bound so the "
             "steady-state ratio tracks max bucket occupancy skew"),
}
with open(os.path.join(REPO, "scripts", "exchange_stats.json"),
          "w") as f:
    json.dump(out, f, indent=1)
print(json.dumps(out))
