"""Sim-dispatch smoke: submit 3 ``kind="sim"`` jobs, drain the queue.

The ISSUE 7 service-mode acceptance drill, end to end in one process
on the stub harness (no reference mount, CPU backend, seconds) —
``serve_demo.py``'s walker-fleet twin:

  clean      a fleet hunt over the tightened-invariant counter spec —
             collects its deduped violations, terminal state
             ``violated``, every unique violation carrying a
             TRACE-format counterexample
  rejected   a spec that fails the speclint frames pass — the
             admission gate kills it at ``queued -> failed``; it
             never reaches ``running`` and costs zero device time
             (the same gate BFS jobs go through)
  preempt    a SIGTERM-style preemption (injected kill mid-chunk) on
             the same hunt — the job requeues with its walker-frontier
             rescue snapshot, resumes, and reports a violation set and
             headline trace BIT-IDENTICAL to the clean job's (the
             fleet's per-(seed, walk-id) determinism contract holding
             across the dispatcher)

Every lifecycle transition must be visible in the per-job journals
(``job_*`` events interleaved with ``sim_chunk``/``hunt_violation``/
``rescue_checkpoint``).

Prints one JSON object; exit 0 iff every expectation holds.

    python scripts/hunt_demo.py
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

if __name__ == "__main__":
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    _fl = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _fl:
        os.environ["XLA_FLAGS"] = (
            _fl + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, REPO)

#: the one hunt configuration all three jobs (and the oracle) share
HUNT_FLAGS = {"stub": True, "inv_x_bound": 2, "walkers": 32,
              "depth": 8, "num": 64, "seed": 1, "chunk_steps": 4}


def main():
    from tpuvsr.obs import read_journal
    from tpuvsr.service.queue import JobQueue
    from tpuvsr.service.worker import Worker

    tmp = tempfile.mkdtemp(prefix="tpuvsr-hunt-demo-")
    out = {"jobs": {}}
    try:
        q = JobQueue(os.path.join(tmp, "spool"))
        clean = q.submit("<stub:hunt-clean>", engine="device",
                         kind="sim", flags=dict(HUNT_FLAGS))
        rejected = q.submit("<stub:hunt-rejected>", engine="device",
                            kind="sim",
                            flags={"stub": True, "stub_bad": True})
        preempt = q.submit("<stub:hunt-preempt>", engine="device",
                           kind="sim",
                           flags=dict(HUNT_FLAGS,
                                      inject="kill@level=1"))
        runs = Worker(q, devices=2).drain()

        checks = {}
        jc = q.get(clean.job_id)
        evs_c = [e["event"]
                 for e in read_journal(q.journal_path(clean.job_id))]
        checks["clean_hunt_violated_with_unique_traces"] = (
            jc.state == "violated"
            and len(jc.result["violations"]) > 1
            and all(v.get("trace") for v in jc.result["violations"])
            and len({v["dedup"] for v in jc.result["violations"]})
            == len(jc.result["violations"]))
        checks["clean_journal_lifecycle"] = (
            ["job_submitted", "job_admitted", "job_started"]
            == [e for e in evs_c if e.startswith("job_")][:3]
            and evs_c[-1] == "job_done"
            and "sim_chunk" in evs_c and "hunt_violation" in evs_c)

        jr = q.get(rejected.job_id)
        evs_r = [e["event"]
                 for e in read_journal(q.journal_path(rejected.job_id))]
        checks["rejected_by_speclint"] = (
            jr.state == "failed" and jr.reason == "speclint"
            and bool((jr.result or {}).get("speclint")))
        checks["rejected_never_ran"] = (
            "job_started" not in evs_r and "run_start" not in evs_r
            and jr.attempts == 0)

        jp = q.get(preempt.job_id)
        evs_p = [e["event"]
                 for e in read_journal(q.journal_path(preempt.job_id))]
        checks["preempt_requeued_then_completed"] = (
            jp.state == "violated" and jp.attempts == 2
            and "job_requeued" in evs_p
            and "rescue_checkpoint" in evs_p)
        checks["preempt_bit_identical_to_clean_hunt"] = (
            jp.result is not None and jc.result is not None
            and jp.result["violations"] == jc.result["violations"]
            and jp.result["trace"] == jc.result["trace"]
            and jp.result["violated"] == jc.result["violated"]
            and jp.result["walks"] == jc.result["walks"])

        for job, evs in ((jc, evs_c), (jr, evs_r), (jp, evs_p)):
            out["jobs"][job.spec] = {
                "state": job.state, "attempts": job.attempts,
                "reason": job.reason, "journal_events": evs,
            }
        out["runs"] = runs
        out["stats"] = q.stats()
        out["unique_violations"] = (len(jc.result["violations"])
                                    if jc.result else 0)
        out["checks"] = checks
        out["ok"] = all(checks.values())
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    print(json.dumps(out, indent=1, default=str))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
