#!/usr/bin/env python
"""Trace-validation drill: round-trip known-good and mutated traces
through the batched validator (ISSUE 8 acceptance demo).

Tier-1 (no reference mount, CPU backend, seconds) on the stub
harness, the drill proves the two directions of the contract:

  accepted   a checker-produced counterexample trace (by construction
             a real spec path) and a batch of recorded genuine walks
             — every one validates end to end, including a partial-
             observation variant (dropped variable + fully-blanked
             events) whose candidate sets do the nondeterminism
             bookkeeping;
  diverged   the same traces with ONE event mutated off the reachable
             transition relation — the validator localizes the first
             divergence at EXACTLY the mutated trace/step and reports
             the spec-side enabled set there, bit-identically between
             the interpreter reference validator and the batched
             device engine.

With the reference corpus mounted, the drill additionally derives a
TRACE.jsonl record from the reference's state-transfer violation
trace dump (``*state_transfer*trace*.txt``, TLC format) and validates
it against VR_STATE_TRANSFER.tla — the real-corpus form of the same
round-trip.

A throughput leg (default 2048 stub traces through the device-mesh
validator) records ``traces_per_s``; ``--out FILE`` writes the JSON
artifact ``bench.py`` attaches to the round doc (the
``scripts/compare_bench.py`` traces/s gate input; cross-backend
comparisons are advisory there).

    python scripts/validate_demo.py [--traces N] [--out FILE]

Prints one JSON object; exit 0 iff every expectation holds.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

if __name__ == "__main__":
    os.environ["JAX_PLATFORMS"] = os.environ.get(
        "TPUVSR_DEMO_BACKEND", "cpu")
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    _fl = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _fl:
        os.environ["XLA_FLAGS"] = (
            _fl + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, REPO)

REFERENCE = "/root/reference/vsr-revisited/paper"


def _reference_roundtrip(out):
    """The reference leg: a record derived from the state-transfer
    violation trace dump validates against VR_STATE_TRANSFER.tla
    (mounted corpora only; absent mount = leg skipped, not failed)."""
    spec_path = os.path.join(
        REFERENCE, "analysis/03-state-transfer/VR_STATE_TRANSFER.tla")
    dumps = glob.glob(os.path.join(
        REFERENCE, "**/*state_transfer*trace*.txt", ), recursive=True)
    if not (os.path.exists(spec_path) and dumps):
        out["reference"] = "skipped (no reference mount)"
        return None
    from tpuvsr.engine.spec import load_spec
    from tpuvsr.frontend.trace_parse import parse_trace_file
    from tpuvsr.validate import host_validate_batch
    from tpuvsr.validate.traces import (record_from_entries,
                                        traces_from_records)
    spec = load_spec(spec_path,
                     os.path.splitext(spec_path)[0] + ".cfg")
    entries = parse_trace_file(dumps[0], spec)
    rec = record_from_entries(entries, tid="st03-violation")
    good = host_validate_batch(
        spec, traces_from_records([rec], spec))
    bad_rec = json.loads(json.dumps(rec))
    ev = bad_rec["events"][len(bad_rec["events"]) // 2]
    var = sorted(ev.get("vars") or {"op": "0"})[0]
    ev.setdefault("vars", {})[var] = "12345"
    bad = host_validate_batch(
        spec, traces_from_records([bad_rec], spec))
    out["reference"] = {
        "dump": os.path.relpath(dumps[0], REFERENCE),
        "events": len(rec["events"]),
        "accepted": good.ok,
        "mutated_diverged_at": (bad.first_divergence or {}).get("step"),
    }
    return good.ok and not bad.ok


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--traces", type=int, default=2048,
                    help="throughput-leg batch size (default 2048)")
    ap.add_argument("--out", default=None, metavar="FILE",
                    help="write the bench attachment JSON to FILE")
    args = ap.parse_args(argv)

    import jax

    from tpuvsr.testing import (counter_spec, stub_model_factory,
                                stub_trace_records, stub_validator)
    from tpuvsr.validate import host_validate_batch
    from tpuvsr.validate.batch import batch_validate
    from tpuvsr.validate.traces import (record_from_entries,
                                        traces_from_records)

    out = {"checks": {}}
    checks = out["checks"]
    spec = counter_spec()

    # -- leg 1: checker-trace round-trip -------------------------------
    # a counterexample the checker itself produced is by construction
    # a real spec path: validating it must accept; shifting one event
    # off the transition relation must diverge exactly there
    from tpuvsr.testing import stub_fleet
    viol = stub_fleet(walkers=32, n_devices=2, inv_x_bound=2).run(
        num=64, depth=8, seed=1)
    rec = record_from_entries(viol.trace, tid="counterexample")
    vspec = counter_spec(inv_x_bound=2)
    good = host_validate_batch(
        vspec, traces_from_records([rec], vspec))
    checks["counterexample_roundtrip_accepted"] = bool(good.ok)
    mut_step = len(rec["events"]) - 1
    bad_rec = json.loads(json.dumps(rec))
    bad_rec["events"][mut_step]["vars"]["x"] = "99"
    bad = host_validate_batch(
        vspec, traces_from_records([bad_rec], vspec))
    fd = bad.first_divergence or {}
    checks["mutated_counterexample_diverges_at_step"] = (
        not bad.ok and fd.get("step") == mut_step
        and bool(fd.get("enabled")))

    # -- leg 2: batch of recorded walks, host vs device ----------------
    recs = stub_trace_records(n=256, depth=6, seed=3, mutate=(100, 2))
    traces = traces_from_records(recs, spec)
    hres = host_validate_batch(spec, traces)
    bres = stub_validator(batch=128, n_devices=2).run(traces)
    checks["device_matches_interpreter"] = (
        json.dumps(bres.divergences, sort_keys=True)
        == json.dumps(hres.divergences, sort_keys=True)
        and bres.accepted == hres.accepted == 255
        and bres.first_divergence["trace"] == "t-0100"
        and bres.first_divergence["step"] == 2)

    # -- leg 3: partial observation ------------------------------------
    part = traces_from_records(
        stub_trace_records(n=64, depth=6, seed=4, drop_vars=("y",),
                           blank_every=3), spec)
    pres = stub_validator(batch=64, n_devices=2).run(part)
    checks["partial_observation_accepted"] = bool(pres.ok)

    # -- leg 4: the reference round-trip (mount-gated) -----------------
    ref_ok = _reference_roundtrip(out)
    if ref_ok is not None:
        checks["reference_roundtrip"] = bool(ref_ok)

    # -- leg 5: throughput ---------------------------------------------
    n = max(64, args.traces)
    big = traces_from_records(
        stub_trace_records(n=n, depth=6, seed=5), spec)
    t0 = time.time()
    tres = batch_validate(spec, big, batch=min(n, 1024),
                          model_factory=stub_model_factory(),
                          confirm=False)
    wall = time.time() - t0
    checks["throughput_batch_accepted"] = bool(tres.ok)
    out.update({
        "backend": jax.default_backend(),
        "devices": len(jax.devices()),
        "traces": tres.traces_checked,
        "batch": min(n, 1024),
        "elapsed_s": round(tres.elapsed, 3),
        "wall_s": round(wall, 3),
        "traces_per_s": round(tres.traces_per_sec, 1),
    })
    out["ok"] = all(checks.values())
    if args.out:
        with open(args.out, "w") as f:
            json.dump(out, f, indent=1, sort_keys=True)
    print(json.dumps(out, indent=1, default=str))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
