"""TPU round-workload driver — DEPRECATED thin wrapper.

The bespoke queue this script used to implement (state json + attempt
log + one-at-a-time subprocess runner) was absorbed by the dispatch
service (ISSUE 6): there is ONE queue implementation now —
``tpuvsr.service`` — and this wrapper only (a) submits the round's
TPU workload as ``kind="shell"`` jobs into a service spool at
``scripts/tpu_spool/`` and (b) gates the drain loop on the axon
tunnel probe, with the original flap rule (a failure with the tunnel
dead afterwards refunds the attempt — the job never really ran).

Durability, claims, attempts, per-job journals and the exit-code ->
state mapping all come from the service; the historical
``scripts/tpu_tests.json`` aggregate is still produced for bench
attachment.  The old ``tpu_queue_state.json`` / ``tpu_queue_log.jsonl``
files are no longer written (the spool's ``jobs.jsonl`` +
``journals/`` supersede them).

Run detached:  python scripts/tpu_queue.py
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPTS = os.path.join(REPO, "scripts")
sys.path.insert(0, REPO)

from tpuvsr.platform_select import probe_tpu          # noqa: E402
from tpuvsr.service.queue import TERMINAL, JobQueue   # noqa: E402
from tpuvsr.service.worker import Worker              # noqa: E402

SPOOL = os.path.join(SCRIPTS, "tpu_spool")
TESTS_OUT = os.path.join(SCRIPTS, "tpu_tests.json")

MODULES = ["vsr", "a01", "i01", "st03", "as04", "rr05", "al05", "cp06"]

ENV_TEST = {"TPUVSR_TEST_BACKEND": "tpu"}
ENV_TPU = {"TPUVSR_TPU": "1"}

# (name, argv, timeout_s, extra_env) — ROUND 5 priority order for
# ~45-min tunnel windows (VERDICT r4 "next round" items; see git
# history of this file for the full rationale per entry)
JOBS = [
    ("miscompile-repro",
     [sys.executable, "scripts/tpu_miscompile_repro.py"], 3600,
     ENV_TPU),
    ("defect-window",
     [sys.executable, "scripts/defect_bfs_window.py",
      "1800", "512", "32"], 3300, ENV_TPU),
    ("bench-fused",
     [sys.executable, "scripts/bench_capture.py"], 2400,
     {**ENV_TPU, "BENCH_FUSED": "1", "BENCH_BUDGET_S": "1800"}),
]
for m in MODULES:
    JOBS.append((f"difftest-{m}",
                 [sys.executable, "-m", "pytest",
                  f"tests/test_{m}_kernel.py",
                  "-q", "-m", "not slow", "--tb=line"], 2400, ENV_TEST))
JOBS += [
    ("liveness-a01-v2t1",
     [sys.executable, "scripts/liveness_shipped.py",
      "a01", "8000000", "512", "16", "2", "1"], 3300, ENV_TPU),
    ("liveness-a01-v1t2",
     [sys.executable, "scripts/liveness_shipped.py",
      "a01", "20000000", "512", "16", "1", "2"], 3600, ENV_TPU),
    ("shipped-pin",
     [sys.executable, "scripts/shipped_pin.py", "1500", "512", "32"],
     2700, ENV_TPU),
    ("sim-scale",
     [sys.executable, "scripts/sim_scale.py",
      "4096", "1500", "1000000"], 2100, ENV_TPU),
    ("sim-scale-wide",
     [sys.executable, "scripts/sim_scale.py",
      "16384", "1500", "1000000", "sim_scale_wide.json"], 2100,
     ENV_TPU),
    ("defect-hunt",
     [sys.executable, "scripts/defect_hunt.py",
      "4096", "48", "1200", "1", "1.0", "guided"], 2000, ENV_TPU),
    ("rr05-deep",
     [sys.executable, "scripts/rr05_deep.py", "1500", "512", "32"],
     2700, ENV_TPU),
    ("defect-window-2",
     [sys.executable, "scripts/defect_bfs_window.py",
      "1800", "512", "32"], 3300, ENV_TPU),
    ("difftest-fused",
     [sys.executable, "-m", "pytest", "tests/test_fused_bfs.py",
      "-q", "--tb=line"], 5400, ENV_TEST),
    ("rr05-deep-2",
     [sys.executable, "scripts/rr05_deep.py", "1500", "512", "32"],
     2700, ENV_TPU),
    ("liveness-i01-v2t1",
     [sys.executable, "scripts/liveness_shipped.py",
      "i01", "8000000", "512", "16", "2", "1"], 3300, ENV_TPU),
    ("shipped-pin-2",
     [sys.executable, "scripts/shipped_pin.py", "1500", "512", "32"],
     2700, ENV_TPU),
    ("liveness-shipped-a01",
     [sys.executable, "scripts/liveness_shipped.py",
      "a01", "25000000", "512", "16"], 3600, ENV_TPU),
]
for m in MODULES:
    JOBS.append((f"difftest-slow-{m}",
                 [sys.executable, "-m", "pytest",
                  f"tests/test_{m}_kernel.py",
                  "-q", "-m", "slow", "--tb=line"], 5400, ENV_TEST))

MAX_ATTEMPTS = 3


def submit_workload(q):
    """Enqueue the round's workload once (idempotent: job ids are the
    workload names; resubmission is skipped)."""
    existing = {j.job_id for j in q.jobs()}
    for i, (name, argv, timeout, extra_env) in enumerate(JOBS):
        if name in existing:
            continue
        # earlier entries run first: the service pops highest priority
        q.submit(name, kind="shell", job_id=name,
                 priority=len(JOBS) - i,
                 flags={"argv": argv, "timeout": timeout,
                        "env": extra_env, "cwd": REPO,
                        "max_attempts": MAX_ATTEMPTS})


def update_tests_json(q):
    tests = {}
    for j in q.jobs():
        if j.job_id.startswith("difftest"):
            r = j.result or {}
            tests[j.job_id] = {"done": j.state == "done",
                               "attempts": j.attempts,
                               "rc": r.get("rc"), "tail": r.get("tail")}
    out = {
        "backend": "tpu (axon tunnel, v5e)",
        "what": ("per-module kernel differential pytest runs executed "
                 "with TPUVSR_TEST_BACKEND=tpu — the device kernels "
                 "held to the interpreter oracle under the real TPU "
                 "lowering (TPU!=CPU lowering caught a real miscompile "
                 "once: device_sim.py lax.switch incident)"),
        "jobs": tests,
        "passed": sum(1 for t in tests.values() if t.get("done")),
        "total": len(tests),
    }
    with open(TESTS_OUT, "w") as f:
        json.dump(out, f, indent=1)


def main():
    q = JobQueue(SPOOL)
    submit_workload(q)
    # flap rule: a nonzero rc with the tunnel dead right after means
    # the job never ran against a live tunnel — refund the attempt
    w = Worker(q, devices=1, log=lambda m: print(m, file=sys.stderr),
               shell_retry_gate=lambda job, rc: probe_tpu(90) <= 0)
    deadline = time.time() + float(
        os.environ.get("TPU_QUEUE_MAX_HOURS", "12")) * 3600
    while time.time() < deadline:
        if not [j for j in q.jobs() if j.state not in TERMINAL]:
            break
        if probe_tpu(90) <= 0:
            time.sleep(180)
            continue
        w.drain(max_jobs=1)
        update_tests_json(q)
    update_tests_json(q)


if __name__ == "__main__":
    main()
