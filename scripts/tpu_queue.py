"""TPU job queue: waits for the flapping axon tunnel and runs the
round's TPU workload whenever the tunnel is up, one job at a time
(the chip is single-tenant), with a hard timeout per job so a mid-job
flap cannot wedge the queue.

The first r4 TPU session proved the failure mode this guards against:
the tunnel came up, bench.py completed on backend "tpu", then the
tunnel died ~25 min later and the in-flight differential pytest hung
forever on a dead RPC (zero CPU, state wait_woken) and had to be
killed.  Probe first, bound everything, record every attempt.

State: scripts/tpu_queue_state.json (job -> done/attempts).
Log:   scripts/tpu_queue_log.jsonl (one line per attempt).
Test results aggregate into scripts/tpu_tests.json (attached to bench).

Run detached:  python scripts/tpu_queue.py
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPTS = os.path.join(REPO, "scripts")
sys.path.insert(0, REPO)

from tpuvsr.platform_select import probe_tpu

STATE = os.path.join(SCRIPTS, "tpu_queue_state.json")
LOG = os.path.join(SCRIPTS, "tpu_queue_log.jsonl")
TESTS_OUT = os.path.join(SCRIPTS, "tpu_tests.json")

MODULES = ["vsr", "a01", "i01", "st03", "as04", "rr05", "al05", "cp06"]

ENV_TEST = {"TPUVSR_TEST_BACKEND": "tpu"}
ENV_TPU = {"TPUVSR_TPU": "1"}

# (name, argv, timeout_s, extra_env) — ROUND 5 priority order for
# ~45-min tunnel windows (VERDICT r4 "next round" items 1-3, 6, 8):
#   1. miscompile repro ladder (localize the tile-1024 TPU divergence;
#      everything else's trust rests on it),
#   2. defect-config paged window on the chip (the graded headline:
#      >=10x the CPU window's 1,160 distinct/s), resumable via
#      checkpoint so flapped windows extend instead of restarting,
#   3. a fresh full bench capture,
#   4. the 7 remaining per-module differential suites under the TPU
#      lowering (difftest-vsr passed in r4, state carries over),
#   5. configs[2] simulation scale + the guided hunt on TPU,
#   6. the RR05 deep pin, extra defect depth, and the slow tier.
JOBS = [
    ("miscompile-repro",
     [sys.executable, "scripts/tpu_miscompile_repro.py"], 3600,
     ENV_TPU),
    ("defect-window",
     [sys.executable, "scripts/defect_bfs_window.py",
      "1800", "512", "32"], 3300, ENV_TPU),
    ("bench-fused",
     [sys.executable, "scripts/bench_capture.py"], 2400,
     {**ENV_TPU, "BENCH_FUSED": "1", "BENCH_BUDGET_S": "1800"}),
]
for m in MODULES:
    JOBS.append((f"difftest-{m}",
                 [sys.executable, "-m", "pytest", f"tests/test_{m}_kernel.py",
                  "-q", "-m", "not slow", "--tb=line"], 2400, ENV_TEST))
JOBS += [
    # shipped-constant runs (VERDICT r4 item 5): the liveness ladder
    # toward the shipped cfg (the fully-shipped space projects past
    # 1e8 states — scripts/a01_shipped_probe.json — so the ladder
    # rungs deliver complete verdicts and the shipped run is an
    # honest bounded attempt, queued later), and the shipped VSR.cfg
    # safety pin (resumable via checkpoint)
    ("liveness-a01-v2t1",
     [sys.executable, "scripts/liveness_shipped.py",
      "a01", "8000000", "512", "16", "2", "1"], 3300, ENV_TPU),
    # |V|=1/timer=2 measured >6M distinct at depth 18 on CPU (the
    # timer axis is the blow-up); raised cap, may still be bounded
    ("liveness-a01-v1t2",
     [sys.executable, "scripts/liveness_shipped.py",
      "a01", "20000000", "512", "16", "1", "2"], 3600, ENV_TPU),
    ("shipped-pin",
     [sys.executable, "scripts/shipped_pin.py", "1500", "512", "32"],
     2700, ENV_TPU),
    # walkers max_seconds num — 4096 reuses the calibrated group caps;
    # the wide job then exploits the TPU's parallel headroom
    ("sim-scale",
     [sys.executable, "scripts/sim_scale.py",
      "4096", "1500", "1000000"], 2100, ENV_TPU),
    ("sim-scale-wide",
     [sys.executable, "scripts/sim_scale.py",
      "16384", "1500", "1000000", "sim_scale_wide.json"], 2100, ENV_TPU),
    # walkers depth max_seconds seed sigma mode
    ("defect-hunt",
     [sys.executable, "scripts/defect_hunt.py",
      "4096", "48", "1200", "1", "1.0", "guided"], 2000, ENV_TPU),
    ("rr05-deep",
     [sys.executable, "scripts/rr05_deep.py", "1500", "512", "32"],
     2700, ENV_TPU),
    # a second window resumes the defect checkpoint and goes deeper
    ("defect-window-2",
     [sys.executable, "scripts/defect_bfs_window.py",
      "1800", "512", "32"], 3300, ENV_TPU),
    # fused-vs-chunked differential ON the TPU lowering
    ("difftest-fused",
     [sys.executable, "-m", "pytest", "tests/test_fused_bfs.py",
      "-q", "--tb=line"], 5400, ENV_TEST),
    ("rr05-deep-2",
     [sys.executable, "scripts/rr05_deep.py", "1500", "512", "32"],
     2700, ENV_TPU),
    ("liveness-i01-v2t1",
     [sys.executable, "scripts/liveness_shipped.py",
      "i01", "8000000", "512", "16", "2", "1"], 3300, ENV_TPU),
    ("shipped-pin-2",
     [sys.executable, "scripts/shipped_pin.py", "1500", "512", "32"],
     2700, ENV_TPU),
    # honest bounded attempt at the fully-shipped liveness constants
    ("liveness-shipped-a01",
     [sys.executable, "scripts/liveness_shipped.py",
      "a01", "25000000", "512", "16"], 3600, ENV_TPU),
]
for m in MODULES:
    JOBS.append((f"difftest-slow-{m}",
                 [sys.executable, "-m", "pytest", f"tests/test_{m}_kernel.py",
                  "-q", "-m", "slow", "--tb=line"], 5400, ENV_TEST))

MAX_ATTEMPTS = 3


def load_state():
    if os.path.exists(STATE):
        with open(STATE) as f:
            return json.load(f)
    return {}


def save_state(st):
    with open(STATE, "w") as f:
        json.dump(st, f, indent=1)


def log(rec):
    rec["ts"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    with open(LOG, "a") as f:
        f.write(json.dumps(rec) + "\n")


def update_tests_json(st):
    tests = {}
    for name, info in st.items():
        if name.startswith("difftest"):
            tests[name] = {k: info.get(k) for k in
                           ("done", "attempts", "rc", "tail")}
    out = {
        "backend": "tpu (axon tunnel, v5e)",
        "what": ("per-module kernel differential pytest runs executed "
                 "with TPUVSR_TEST_BACKEND=tpu — the device kernels "
                 "held to the interpreter oracle under the real TPU "
                 "lowering (TPU!=CPU lowering caught a real miscompile "
                 "once: device_sim.py lax.switch incident)"),
        "jobs": tests,
        "passed": sum(1 for t in tests.values() if t.get("done")),
        "total": len(tests),
    }
    with open(TESTS_OUT, "w") as f:
        json.dump(out, f, indent=1)


def run_job(name, argv, timeout, extra_env):
    env = dict(os.environ)
    env.update(extra_env)
    t0 = time.time()
    try:
        p = subprocess.Popen(argv, cwd=REPO, env=env,
                             stdout=subprocess.PIPE,
                             stderr=subprocess.STDOUT, text=True,
                             start_new_session=True)
        try:
            out, _ = p.communicate(timeout=timeout)
            rc = p.returncode
        except subprocess.TimeoutExpired:
            os.killpg(p.pid, signal.SIGKILL)
            out, _ = p.communicate()
            rc = -9
    except Exception as e:  # noqa: BLE001
        return -1, f"launcher error: {e}", time.time() - t0
    tail = "\n".join((out or "").strip().splitlines()[-6:])
    return rc, tail, time.time() - t0


def main():
    st = load_state()
    deadline = time.time() + float(
        os.environ.get("TPU_QUEUE_MAX_HOURS", "12")) * 3600
    while time.time() < deadline:
        pending = [j for j in JOBS
                   if not st.get(j[0], {}).get("done")
                   and st.get(j[0], {}).get("attempts", 0) < MAX_ATTEMPTS]
        if not pending:
            log({"event": "queue-drained"})
            break
        n = probe_tpu(90)
        if n <= 0:
            log({"event": "tunnel-down"})
            time.sleep(180)
            continue
        name, argv, timeout, extra_env = pending[0]
        log({"event": "start", "job": name})
        rc, tail, el = run_job(name, argv, timeout, extra_env)
        info = st.setdefault(name, {"attempts": 0})
        # a failure with the tunnel dead afterwards is a flap, not a
        # job failure: the conftest probe-refusal, a -9 hard timeout,
        # or a mid-job RPC hang all leave rc!=0 without the job ever
        # running against a live tunnel — don't burn an attempt
        flap = rc != 0 and probe_tpu(90) <= 0
        if not flap:
            info["attempts"] += 1
        info["rc"] = rc
        info["tail"] = tail
        info["elapsed_s"] = round(el, 1)
        info["done"] = (rc == 0)
        save_state(st)
        update_tests_json(st)
        log({"event": "finish", "job": name, "rc": rc, "flap": flap,
             "elapsed_s": round(el, 1), "tail": tail[-400:]})
    log({"event": "queue-exit"})


if __name__ == "__main__":
    main()
