"""Bounded-window BFS of the defect fixture through the paged engine.

The reference's flagship run — exhaustive BFS of VSR.tla at R=3,
|Values|=3, timer=3 — took "multiple days" and >=500 GB of disk
(/root/reference/README.md:20).  This script runs the same fixture
(examples/VSR_defect.cfg) through the host-paged BFS engine for a fixed
wall-clock window and records sustained throughput, memory behavior,
spill statistics, frontier occupancy, and a measured time-to-depth-24
projection (the violation depth: TRACE:556) — the single-chip version
of the reference's headline workload.

Checkpoint/resume: the run snapshots at level boundaries
(scripts/defect_window_ckpt) and RESUMES from the snapshot when one
exists — a tunnel flap mid-window costs only the partial level, and
re-running the job goes deeper instead of starting over.  Delete the
checkpoint dir to start fresh.

Writes scripts/defect_window.json (cumulative across resumed windows).

Usage: python scripts/defect_bfs_window.py [seconds] [tile] [chunk_tiles]
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tpuvsr.platform_select import ensure_backend

backend = ensure_backend(log=lambda m: print(f"[defect_window] {m}",
                                             flush=True))

from tpuvsr.engine.paged_bfs import PagedBFS          # noqa: E402
from tpuvsr.engine.spec import load_spec              # noqa: E402
from tpuvsr.obs import RunObserver                    # noqa: E402

seconds = float(sys.argv[1]) if len(sys.argv) > 1 else 600.0
tile = int(sys.argv[2]) if len(sys.argv) > 2 else 256
chunk_tiles = int(sys.argv[3]) if len(sys.argv) > 3 else 16

CKPT = os.path.join(REPO, "scripts", "defect_window_ckpt")
OUT = os.path.join(REPO, "scripts", "defect_window.json")
# round-artifact trajectories (ISSUE 3 satellite / ROADMAP follow-up):
# the journal appends across resumed windows — one continuous event
# stream for the whole checkpoint/recover chain — and the metrics file
# carries the last window's per-level rows + phase timers
JOURNAL = os.path.join(REPO, "scripts", "defect_window.jsonl")
METRICS = os.path.join(REPO, "scripts", "defect_window_metrics.json")

REFERENCE = os.environ.get(
    "TPUVSR_REFERENCE", "/root/reference/vsr-revisited/paper")
spec = load_spec(f"{REFERENCE}/VSR.tla",
                 f"{REPO}/examples/VSR_defect.cfg")

t0 = time.time()
eng = PagedBFS(spec, tile_size=tile, chunk_tiles=chunk_tiles,
               next_capacity=1 << 17, fpset_capacity=1 << 24,
               max_msgs=32)
from tpuvsr.engine.checkpoint import prior_elapsed  # noqa: E402

resume = CKPT if os.path.isdir(CKPT) else None
prev_elapsed = prior_elapsed(CKPT) if resume else 0.0
if resume:
    print(f"[defect_window] resuming from {CKPT}", flush=True)
res = eng.run(max_seconds=prev_elapsed + seconds, resume_from=resume,
              checkpoint_path=CKPT, checkpoint_every=120.0,
              obs=RunObserver(journal_path=JOURNAL, metrics_path=METRICS),
              log=lambda m: print(f"[defect_window] {m}", flush=True))
window_elapsed = time.time() - t0          # this window's wall clock
elapsed = res.elapsed                      # cumulative across resumes


def depth24_projection(level_sizes, distinct_per_s):
    """Fit the tail growth ratio of the level sizes and project the
    cumulative states through depth 24 (the violation depth), then
    divide by the sustained distinct/s.  Crude but measured."""
    full = [s for s in level_sizes if s > 0]
    if len(full) < 4 or distinct_per_s <= 0:
        return None
    # fit on the last 3 COMPLETED levels (the final entry is partial
    # whenever the window cut mid-level) and seed the extrapolation
    # from the last completed level too — seeding from the partial one
    # would understate the projection by its completion fraction
    tail = full[-4:-1]
    ratios = [tail[i + 1] / tail[i] for i in range(len(tail) - 1)
              if tail[i] > 0]
    if not ratios:
        return None
    r = sum(ratios) / len(ratios)
    total = sum(full[:-1])
    cur = full[-2]
    for _ in range(len(full) - 2, 24):
        cur *= r
        total += cur
    return {"tail_growth_ratio": round(r, 2),
            "projected_cumulative_states_depth24": int(total),
            "projected_seconds_at_current_rate":
                int(total / distinct_per_s)}


distinct_per_s = res.distinct_states / max(elapsed, 1e-9)
out = {
    "config": "examples/VSR_defect.cfg (R=3, |Values|=3, timer=3)",
    "engine": "paged (host-RAM frontier, HBM fingerprints)",
    "backend": backend,
    "window_s": seconds,
    "tile": tile,
    "chunk_tiles": chunk_tiles,
    "elapsed_s": round(elapsed, 1),
    "window_elapsed_s": round(window_elapsed, 1),
    "resumed": bool(resume),
    "depth_reached": res.diameter,
    "distinct_states": res.distinct_states,
    "states_generated": res.states_generated,
    "distinct_per_s": round(distinct_per_s, 1),
    "generated_per_s": round(res.states_generated / max(elapsed, 1e-9),
                             1),
    "vs_cpu_window_1160": round(distinct_per_s / 1160.3, 2),
    "level_sizes": eng.level_sizes,
    "frontier_final": eng.level_sizes[-1] if eng.level_sizes else 0,
    "avg_tile_occupancy": round(
        sum(eng.level_sizes) / max(1, len(eng.level_sizes)) / tile, 1),
    "spill_count": eng.spill_count,
    "spill_rows": eng.spill_rows,
    "max_msgs_final": eng.codec.shape.MAX_MSGS,
    # the packed row when -pack is on (ISSUE 9) — the bytes the paged
    # tier ACTUALLY moves per state; pack_ratio records the cut
    "frontier_bytes_per_state": eng._state_row_bytes(),
    "pack_ratio": round(
        sum(v.nbytes for v in eng.codec.zero_state().values())
        / eng._state_row_bytes(), 2),
    "device_bytes_per_s": round(
        (res.states_generated + res.distinct_states)
        * eng._state_row_bytes() / max(elapsed, 1e-9) / 1e6, 1),
    "depth24_projection": depth24_projection(
        eng.level_sizes, distinct_per_s),
    "violated": res.violated_invariant,
    "error": res.error,
    "ok": res.ok,
    "journal": "scripts/defect_window.jsonl",
    "metrics_file": "scripts/defect_window_metrics.json",
    "phases": (res.metrics or {}).get("phases"),
    "counters": (res.metrics or {}).get("counters"),
    # ISSUE 10 acceptance surface: the occupancy-packed fused commit's
    # real-work fraction and its one-insert-per-tile structure
    "commit": (res.metrics or {}).get("gauges", {}).get("commit_mode"),
    "occupancy": (res.metrics or {}).get("gauges", {}).get("occupancy"),
    "inserts_per_tile": (res.metrics or {}).get(
        "gauges", {}).get("inserts_per_tile"),
}
with open(OUT, "w") as f:
    json.dump(out, f, indent=1)
print(json.dumps(out))
