"""Bounded-window BFS of the defect fixture through the paged engine.

The reference's flagship run — exhaustive BFS of VSR.tla at R=3,
|Values|=3, timer=3 — took "multiple days" and >=500 GB of disk
(/root/reference/README.md:20).  This script runs the same fixture
(examples/VSR_defect.cfg) through the host-paged BFS engine for a fixed
wall-clock window and records sustained throughput, memory behavior,
and spill statistics — the capability proof that a defect-scale level
no longer OOMs the engine (VERDICT r3 item 2).

Writes scripts/defect_window.json.

Usage: python scripts/defect_bfs_window.py [seconds] [tile] [chunk_tiles]
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tpuvsr.platform_select import ensure_backend

backend = ensure_backend(log=lambda m: print(f"[defect_window] {m}",
                                             flush=True))

from tpuvsr.engine.paged_bfs import PagedBFS          # noqa: E402
from tpuvsr.engine.spec import load_spec              # noqa: E402

seconds = float(sys.argv[1]) if len(sys.argv) > 1 else 600.0
tile = int(sys.argv[2]) if len(sys.argv) > 2 else 256
chunk_tiles = int(sys.argv[3]) if len(sys.argv) > 3 else 16

REFERENCE = os.environ.get(
    "TPUVSR_REFERENCE", "/root/reference/vsr-revisited/paper")
spec = load_spec(f"{REFERENCE}/VSR.tla",
                 f"{REPO}/examples/VSR_defect.cfg")

t0 = time.time()
eng = PagedBFS(spec, tile_size=tile, chunk_tiles=chunk_tiles,
               next_capacity=1 << 16, fpset_capacity=1 << 22)
compile_probe = time.time()
res = eng.run(max_seconds=seconds,
              log=lambda m: print(f"[defect_window] {m}", flush=True))
elapsed = res.elapsed
out = {
    "config": "examples/VSR_defect.cfg (R=3, |Values|=3, timer=3)",
    "engine": "paged (host-RAM frontier, HBM fingerprints)",
    "backend": backend,
    "window_s": seconds,
    "tile": tile,
    "chunk_tiles": chunk_tiles,
    "elapsed_s": round(elapsed, 1),
    "depth_reached": res.diameter,
    "distinct_states": res.distinct_states,
    "states_generated": res.states_generated,
    "distinct_per_s": round(res.distinct_states / elapsed, 1),
    "generated_per_s": round(res.states_generated / elapsed, 1),
    "level_sizes": eng.level_sizes,
    "spill_count": eng.spill_count,
    "spill_rows": eng.spill_rows,
    "max_msgs_final": eng.codec.shape.MAX_MSGS,
    "frontier_bytes_per_state": sum(
        v.nbytes for v in eng.codec.zero_state().values()),
    "violated": res.violated_invariant,
    "error": res.error,
    "ok": res.ok,
}
with open(os.path.join(REPO, "scripts", "defect_window.json"), "w") as f:
    json.dump(out, f, indent=1)
print(json.dumps(out))
