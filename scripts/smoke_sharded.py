"""Smoke the ShardedBFS driver on the virtual 8-device CPU mesh:
depth-limited run must match the single-device DeviceBFS level sizes."""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np
import jax

jax.config.update(
    "jax_compilation_cache_dir", os.path.join(REPO, ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)

from jax.sharding import Mesh
from tests.conftest import vsr_spec
from tpuvsr.engine.device_bfs import DeviceBFS
from tpuvsr.parallel.sharded_bfs import ShardedBFS

DEPTH = int(sys.argv[1]) if len(sys.argv) > 1 else 4

spec = vsr_spec()
mesh = Mesh(np.array(jax.devices()[:8]), ("d",))
sbfs = ShardedBFS(spec, mesh, tile=16, bucket_cap=512,
                  next_capacity=1 << 10, fpset_capacity=1 << 12)
res = sbfs.run(max_depth=DEPTH, log=print)
print("sharded:", res.ok, res.distinct_states, res.states_generated,
      res.error, "levels:", sbfs.level_sizes)

eng = DeviceBFS(spec, tile_size=64)
res1 = eng.run(max_depth=DEPTH, log=print)
print("single :", res1.ok, res1.distinct_states, res1.states_generated,
      res1.error, "levels:", eng.level_sizes)
assert sbfs.level_sizes == eng.level_sizes, "level sizes differ"
assert res.distinct_states == res1.distinct_states
print("MATCH")
