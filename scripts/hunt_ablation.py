"""Hunt ablation (VERDICT r3 item 6): measure time-to-violation of the
state-transfer defect for each sampling mode on fixed seeds.

Modes: uniform (TLC's uniform-over-successors), flat (two-stage,
action-uniform — the round-3 default), weighted (real defect-path
weights), guided (weighted + importance splitting).  Each (mode, seed)
runs scripts/defect_hunt.py in a subprocess with a shared wall-clock
budget; a run that ends without a violation records a timeout at the
budget.  Results append to scripts/hunt_ablation.json after every run
so a killed sweep keeps its finished rows.

Usage: python scripts/hunt_ablation.py [budget_s] [seeds] [walkers] [depth]
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "scripts", "hunt_ablation.json")

budget = float(sys.argv[1]) if len(sys.argv) > 1 else 1500.0
seeds = int(sys.argv[2]) if len(sys.argv) > 2 else 3
walkers = int(sys.argv[3]) if len(sys.argv) > 3 else 4096
depth = int(sys.argv[4]) if len(sys.argv) > 4 else 40

MODES = ["uniform", "flat", "weighted", "guided"]

results = {"budget_s": budget, "walkers": walkers, "depth": depth,
           "runs": []}
if os.path.exists(OUT):
    with open(OUT) as f:
        results = json.load(f)

done = {(r["mode"], r["seed"]) for r in results["runs"]}

for mode in MODES:
    for seed in range(1, seeds + 1):
        if (mode, seed) in done:
            continue
        print(f"=== {mode} seed {seed}", flush=True)
        t0 = time.time()
        p = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts",
                                          "defect_hunt.py"),
             str(walkers), str(depth), str(budget), str(seed), "1.0",
             mode],
            capture_output=True, text=True,
            timeout=budget * 2 + 3600)
        row = {"mode": mode, "seed": seed,
               "elapsed_s": round(time.time() - t0, 1)}
        hit = None
        for line in p.stdout.splitlines():
            if line.startswith("{") and "time_to_violation_s" in line:
                hit = json.loads(line)
        if hit:
            row.update(time_to_violation_s=hit["time_to_violation_s"],
                       steps=hit["steps"], walks=hit["walks"],
                       trace_len=hit["trace_len"], violated=True)
        else:
            row.update(time_to_violation_s=None, violated=False,
                       note=f"no violation within {budget}s budget")
        results["runs"].append(row)
        print(f"  -> {row}", flush=True)
        with open(OUT, "w") as f:
            json.dump(results, f, indent=1)
print("done")
