"""Defect-reproduction hunt: find the state-transfer data-loss
violation (reference README:11-18, state_transfer_violation_trace.txt)
with the SHARDED WALKER FLEET (tpuvsr/sim, ISSUE 7) on the defect
fixture config.

Uses weighted two-stage action sampling + swarm scheduler noise
(uniform-over-successors walks are dominated by message-delivery lanes
and essentially never thread the SendGetState truncation window), and
— in guided mode — fingerprint-novelty importance splitting with the
VSR kernel's ``hunt_score`` blended in (``tpuvsr/sim/splitting.py``).

Usage: python scripts/defect_hunt.py [walkers] [depth] [max_seconds]
       [seed] [swarm_sigma] [mode]

Modes (the r4 ablation axis, VERDICT item 6):
  uniform  — TLC's uniform-over-successors draw (no action weighting)
  flat     — two-stage sampling, uniform over enabled ACTIONS
  weighted — two-stage sampling with real weights biased toward the
             defect path (SendGetState truncation + view changes)
  guided   — weighted + importance splitting (novelty + hunt_score
             kill/clone resampling)
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tpuvsr.platform_select import force_cpu
if os.environ.get("TPUVSR_TPU") != "1":
    force_cpu()

walkers = int(sys.argv[1]) if len(sys.argv) > 1 else 4096
depth = int(sys.argv[2]) if len(sys.argv) > 2 else 48
max_seconds = float(sys.argv[3]) if len(sys.argv) > 3 else 600
seed = int(sys.argv[4]) if len(sys.argv) > 4 else 0
sigma = float(sys.argv[5]) if len(sys.argv) > 5 else 1.0
mode = sys.argv[6] if len(sys.argv) > 6 else os.environ.get(
    "TPUVSR_HUNT_MODE",
    "guided" if os.environ.get("TPUVSR_HUNT_GUIDED", "1") == "1"
    else "flat")

# Real action weights biased toward the defect path: the violation
# needs view changes interleaved with the SendGetState truncation
# (VSR.tla:491-516) and the final ReceiveSV log wipe (TRACE:554-577);
# unlisted actions weigh 1.
WEIGHTS = {
    "TimerSendSVC": 3.0,
    "SendGetState": 6.0,
    "SendDVC": 2.0,
    "SendSV": 2.0,
    "ReceiveSV": 2.0,
    "ReceiveClientRequest": 2.0,
}

MODES = {
    "uniform": dict(action_weights=None, split=False, swarm=0.0),
    "flat": dict(action_weights={}, split=False, swarm=sigma),
    "weighted": dict(action_weights=WEIGHTS, split=False, swarm=sigma),
    "guided": dict(action_weights=WEIGHTS, split=True, swarm=sigma),
}
mcfg = MODES[mode]

from tpuvsr.engine.spec import SpecModel
from tpuvsr.frontend.cfg import parse_cfg_file
from tpuvsr.frontend.parser import parse_module_file
from tpuvsr.sim.fleet import FleetSimulator
from tpuvsr.sim.splitting import NoveltySplitter

REFERENCE = os.environ.get(
    "TPUVSR_REFERENCE", "/root/reference/vsr-revisited/paper")

mod = parse_module_file(f"{REFERENCE}/VSR.tla")
cfg = parse_cfg_file(f"{REPO}/examples/VSR_defect.cfg")
spec = SpecModel(mod, cfg)

import jax
print(f"backend: {jax.default_backend()}", file=sys.stderr)

split = (NoveltySplitter(frac=0.25, decay=0.5, hunt_beta=1.5)
         if mcfg["split"] else None)
t0 = time.time()
sim = FleetSimulator(spec, walkers=walkers, chunk_steps=8, max_msgs=48,
                     action_weights=mcfg["action_weights"],
                     swarm_sigma=mcfg["swarm"], split=split)
print(f"build: {time.time()-t0:.1f}s mode={mode} walkers={walkers} "
      f"mesh={sim.D} (compile on first chunk)",
      file=sys.stderr, flush=True)

t0 = time.time()
res = sim.run(num=10**9, depth=depth, seed=seed,
              max_seconds=max_seconds,
              log=lambda m: print(f"hunt: {m} ({time.time()-t0:.0f}s)",
                                  file=sys.stderr))
ttv = time.time() - t0
print(f"\nelapsed {res.elapsed:.1f}s, walks {res.walks}, steps {res.steps}")
print(f"ok={res.ok} violated={res.violated_invariant}")
if res.trace:
    print(f"trace length {len(res.trace)}")
    for te in res.trace:
        print(f"  {te.position}: {te.action_name}")
    last = res.trace[-1].state
    print("final logs:", last["rep_log"])
    print("acked:", last["aux_client_acked"])
    result = {"time_to_violation_s": round(ttv, 1),
              "violated": res.violated_invariant,
              "engine": "fleet-sim",
              "walkers": walkers, "mesh_devices": sim.D,
              "depth": depth, "seed": seed,
              "swarm_sigma": mcfg["swarm"],
              "split_enabled": bool(mcfg["split"]),
              "mode": mode,
              "walks": res.walks, "steps": res.steps,
              "trace_len": len(res.trace),
              "final_action": res.trace[-1].action_name,
              "backend": jax.default_backend()}
    print(json.dumps(result))
    with open(os.path.join(REPO, "scripts", "hunt_result.json"), "w") as f:
        json.dump(result, f, indent=1)
    from tpuvsr.engine.trace import format_trace, format_trace_te
    with open(os.path.join(REPO, "scripts", "hunt_trace.txt"), "w") as f:
        f.write(format_trace(res.trace))
    # replayable artifact (frontend.trace_parse format).  Written to
    # scripts/ — the committed golden at examples/found_violation_trace
    # .txt is promoted manually after replay validation, so a later
    # hunt with a different witness shape can't silently clobber it
    with open(os.path.join(REPO, "scripts",
                           "found_violation_trace.txt"), "w") as f:
        f.write(format_trace_te(res.trace))
