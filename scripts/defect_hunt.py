"""Defect-reproduction experiment: hunt the state-transfer data-loss
violation (reference README:11-18, state_transfer_violation_trace.txt)
with the device simulator on the defect fixture config.

Usage: python scripts/defect_hunt.py [walkers] [depth] [max_seconds] [seed]
"""

import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

walkers = int(sys.argv[1]) if len(sys.argv) > 1 else 4096
depth = int(sys.argv[2]) if len(sys.argv) > 2 else 64
max_seconds = float(sys.argv[3]) if len(sys.argv) > 3 else 600
seed = int(sys.argv[4]) if len(sys.argv) > 4 else 0

from tpuvsr.engine.spec import SpecModel
from tpuvsr.frontend.cfg import parse_cfg_file
from tpuvsr.frontend.parser import parse_module_file
from tpuvsr.engine.device_sim import DeviceSimulator

REFERENCE = os.environ.get(
    "TPUVSR_REFERENCE", "/root/reference/vsr-revisited/paper")

mod = parse_module_file(f"{REFERENCE}/VSR.tla")
cfg = parse_cfg_file(f"{REPO}/examples/VSR_defect.cfg")
spec = SpecModel(mod, cfg)

import jax
print(f"backend: {jax.default_backend()}", file=sys.stderr)

t0 = time.time()
sim = DeviceSimulator(spec, walkers=walkers, chunk_steps=32, max_msgs=48)
print(f"build: {time.time()-t0:.1f}s", file=sys.stderr)

t0 = time.time()
res = sim.run(num=10**9, depth=depth, seed=seed,
              max_seconds=max_seconds,
              log=lambda m: print(f"hunt: {m} ({time.time()-t0:.0f}s)",
                                  file=sys.stderr))
print(f"\nelapsed {res.elapsed:.1f}s, walks {res.walks}, steps {res.steps}")
print(f"ok={res.ok} violated={res.violated_invariant}")
if res.trace:
    print(f"trace length {len(res.trace)}")
    for te in res.trace:
        print(f"  {te.position}: {te.action_name}")
    last = res.trace[-1].state
    print("final logs:", last["rep_log"])
    print("acked:", last["aux_client_acked"])
