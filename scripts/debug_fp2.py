"""Rerun the exact defect hunt, pickle the trace + dense states for
offline analysis, and print a detailed per-key diff at the first
interpreter-validation failure."""

import os
import pickle
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np
import jax.numpy as jnp

from tpuvsr.engine.spec import SpecModel
from tpuvsr.frontend.cfg import parse_cfg_file
from tpuvsr.frontend.parser import parse_module_file
from tpuvsr.engine.device_sim import DeviceSimulator

REFERENCE = "/root/reference/vsr-revisited/paper"
mod = parse_module_file(f"{REFERENCE}/VSR.tla")
cfg = parse_cfg_file(f"{REPO}/examples/VSR_defect.cfg")
spec = SpecModel(mod, cfg)

sim = DeviceSimulator(spec, walkers=4096, chunk_steps=32, max_msgs=48)
trace = None
try:
    res = sim.run(num=10**9, depth=64, seed=0, max_seconds=900,
                  log=lambda m: print(f"hunt: {m}", file=sys.stderr))
    trace = res.trace
    print(f"ok={res.ok} violated={res.violated_invariant} steps={res.steps}")
except Exception as e:
    print(f"EXCEPTION: {type(e).__name__}: {e}")
    trace = getattr(e, "trace", None)
if trace is None:
    sys.exit("no violation found")


class TE:
    pass


res_trace = trace


class R:
    trace = res_trace


res = R()

with open("/tmp/defect_trace.pkl", "wb") as f:
    pickle.dump([(te.position, te.action_name, te.state)
                 for te in res.trace], f)
print("pickled trace to /tmp/defect_trace.pkl")

cur = res.trace[0].state
for te in res.trace[1:]:
    cands = [succ for a, succ in spec.successors(cur)
             if a.name == te.action_name]
    exact = [s for s in cands if s == te.state]
    if not exact:
        print(f"STEP {te.position} ({te.action_name}): no exact match "
              f"among {len(cands)} interp candidates")
        # diff against the closest candidate (fewest differing keys)
        best, bestdiff = None, None
        for s in cands:
            diff = [k for k in s if s[k] != te.state.get(k)]
            if bestdiff is None or len(diff) < len(bestdiff):
                best, bestdiff = s, diff
        if best is None:
            print("  (no candidates at all)")
        else:
            print(f"  closest candidate differs on {bestdiff}")
            for k in bestdiff:
                print(f"  {k}:\n    interp: {best[k]}\n"
                      f"    replay: {te.state.get(k)}")
        extra = set(te.state) - set(cur)
        missing = set(cur) - set(te.state)
        if extra or missing:
            print(f"  key-set drift: extra={extra} missing={missing}")
        break
    cur = te.state
else:
    print("full trace validates against interpreter")
