"""Serving-tier demo: lifecycle smoke + the ISSUE 14 saturation drill
+ the ISSUE 18 abuse drill.

Five legs, end to end on the stub harness (no reference mount, CPU
backend), printing one JSON object; exit 0 iff every check holds:

  lifecycle   the original ISSUE 6 three-job drill (clean /
              speclint-rejected / preempt-requeue-bit-identical) —
              unchanged, now riding the fair-share pop order.

  saturation  the ISSUE 14 acceptance drill: HUNDREDS of queued jobs
              across 3 tenants and all four job kinds (shell, check,
              sim, validate) drained by 2 *worker processes* over one
              spool.  Checks: no starvation (every tenant's jobs all
              reach a terminal state, engine verdicts exact), fair
              interleaving (each tenant's mean completion rank stays
              near the global mean — no tenant waits for the others
              to finish), both workers actually claim work, and every
              job is claimed exactly once (attempts == job_started
              count per journal).

  scaling     near-linear worker scaling on sleep-shell jobs: the
              2-worker drain rate must be >= 1.6x the 1-worker rate
              (rates measured first-claim -> last-terminal off the
              spool log, so process startup is excluded).

  bit_identity byte-identical outcomes vs single-worker serial drain:
              the same deterministic job set (violating check, clean
              check, mutated-trace interp validate, seeded fleet
              hunt, shell) drained serially and by 2 concurrent
              workers; results and journals must agree modulo
              timestamps/worker-id (the projection below).

  abuse       the ISSUE 18 hardened-front-door drill: an
              unauthenticated client (401), a flooding tenant (429
              with Retry-After off the per-tenant token bucket) and
              an oversized body (413) are all rejected at the door,
              every denial is journaled and folded onto /v1/metrics,
              and the legit tenant's job still completes with the
              exact stub fixpoint.

    python scripts/serve_demo.py [--spool-driver fs|objstore|quorum]

``--spool-driver`` (ISSUE 20) runs every leg's spool over the named
spool driver — the acceptance bar is that the saturation leg passes
UNCHANGED over ``quorum`` (the replicated control log carries the
same exactly-once story as one filesystem).

Sizes honor TPUVSR_DEMO_SHELL_JOBS / TPUVSR_DEMO_SCALE_JOBS for
heavier manual runs; the defaults keep the whole demo tier-1 friendly.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

if __name__ == "__main__":
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    _fl = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _fl:
        os.environ["XLA_FLAGS"] = (
            _fl + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, REPO)

N_SHELL = int(os.environ.get("TPUVSR_DEMO_SHELL_JOBS", "192"))
N_SCALE = int(os.environ.get("TPUVSR_DEMO_SCALE_JOBS", "20"))
#: long enough that the sleep dominates per-job queue overhead (claim
#: fsyncs + subprocess spawn), so the ratio reads WORKER parallelism
SCALE_SLEEP = 0.3
TENANTS = ("acme", "blue", "cobra")

#: the spool driver every leg runs over (--spool-driver; None = fs).
#: Only NEW-spool creations pass it; re-opens auto-detect from the
#: spool's persisted spooldrv.json (the ISSUE 20 contract).
SPOOL_DRIVER = None


def _new_queue(spool, **kw):
    from tpuvsr.service.queue import JobQueue
    if SPOOL_DRIVER:
        kw.setdefault("driver", SPOOL_DRIVER)
    return JobQueue(spool, **kw)

#: the journal projection for the bit-identity oracle — everything a
#: run MEANS, nothing about when/where it ran ("journals modulo
#: timestamps/worker-id")
STABLE_EVENT_KEYS = {
    "level_done": ("depth", "frontier", "distinct", "generated"),
    "violation": ("kind", "name"),
    "divergence": ("trace", "step"),
    "hunt_violation": ("name", "walk", "depth"),
    "run_end": ("ok",),
    "job_done": ("state",),
}


def _true_argv():
    from tpuvsr.testing import true_argv
    return true_argv()


def _sleep_argv(seconds):
    return [sys.executable, "-c", f"import time; time.sleep({seconds})"]


def _strip_volatile(result):
    if not isinstance(result, dict):
        return result
    return {k: v for k, v in result.items()
            if k not in ("elapsed_s", "supervisor")
            and "per_s" not in k}


def _journal_projection(q, job_id):
    from tpuvsr.obs import read_journal
    out = []
    for ev in read_journal(q.journal_path(job_id)):
        keys = STABLE_EVENT_KEYS.get(ev["event"])
        if keys:
            out.append((ev["event"],) + tuple(ev.get(k) for k in keys))
    return out


# ---------------------------------------------------------------------
# leg 1: lifecycle (the original ISSUE 6 drill)
# ---------------------------------------------------------------------
def demo_lifecycle(tmp, out):
    from tpuvsr.obs import read_journal
    from tpuvsr.service.queue import JobQueue
    from tpuvsr.service.worker import Worker, result_summary
    from tpuvsr.testing import STUB_DISTINCT, STUB_LEVELS

    q = _new_queue(os.path.join(tmp, "spool-life"))
    clean = q.submit("<stub:clean>", engine="device",
                     flags={"stub": True})
    rejected = q.submit("<stub:rejected>", engine="device",
                        flags={"stub": True, "stub_bad": True})
    preempt = q.submit("<stub:preempt>", engine="device",
                       flags={"stub": True, "inv_x_bound": 2,
                              "inject": "kill@level=2"})
    runs = Worker(q, devices=2).drain()

    from tpuvsr.engine.device_bfs import DeviceBFS
    from tpuvsr.testing import counter_spec, stub_model_factory
    eng = DeviceBFS(counter_spec(inv_x_bound=2),
                    model_factory=stub_model_factory(inv_x_bound=2),
                    hash_mode="full", tile_size=4,
                    fpset_capacity=1 << 8, next_capacity=1 << 6)
    preempt_oracle = result_summary(eng.run())

    checks = {}
    jc = q.get(clean.job_id)
    evs_c = [e["event"]
             for e in read_journal(q.journal_path(clean.job_id))]
    checks["clean_done_exact_fixpoint"] = (
        jc.state == "done"
        and jc.result["distinct"] == STUB_DISTINCT
        and jc.result["levels"] == STUB_LEVELS)
    checks["clean_journal_lifecycle"] = (
        ["job_submitted", "job_admitted", "job_started"]
        == [e for e in evs_c if e.startswith("job_")][:3]
        and evs_c[-1] == "job_done")

    jr = q.get(rejected.job_id)
    evs_r = [e["event"]
             for e in read_journal(q.journal_path(rejected.job_id))]
    checks["rejected_by_speclint"] = (
        jr.state == "failed" and jr.reason == "speclint"
        and bool((jr.result or {}).get("speclint")))
    checks["rejected_never_ran"] = (
        "job_started" not in evs_r and "run_start" not in evs_r
        and jr.attempts == 0)

    jp = q.get(preempt.job_id)
    evs_p = [e["event"]
             for e in read_journal(q.journal_path(preempt.job_id))]
    checks["preempt_requeued_then_completed"] = (
        jp.state == "violated" and jp.attempts == 2
        and "job_requeued" in evs_p
        and "rescue_checkpoint" in evs_p)
    checks["preempt_bit_identical_to_oracle"] = (
        jp.result is not None
        and jp.result.get("violated") == preempt_oracle.get("violated")
        and jp.result.get("trace") == preempt_oracle.get("trace")
        and jp.result["distinct"] == preempt_oracle["distinct"])

    out["lifecycle"] = {"runs": runs, "stats": q.stats(),
                        "checks": checks}
    return checks


# ---------------------------------------------------------------------
# leg 2: saturation — hundreds of jobs, 3 tenants, 4 kinds, 2 workers
# ---------------------------------------------------------------------
def demo_saturation(tmp, out):
    from tpuvsr.obs import read_journal
    from tpuvsr.serve.fairshare import FairSharePolicy
    from tpuvsr.serve.pool import WorkerPool
    from tpuvsr.service.queue import JobQueue
    from tpuvsr.testing import stub_trace_records, subprocess_env
    from tpuvsr.validate import save_traces

    spool = os.path.join(tmp, "spool-sat")
    q = _new_queue(spool)
    true_argv = _true_argv()
    age_every = 0.5

    shell_ids = []
    for i in range(N_SHELL):
        j = q.submit(f"shell-{i:03d}", kind="shell",
                     tenant=TENANTS[i % 3],
                     priority=(5 if i % 7 == 0 else 0),
                     flags={"argv": true_argv, "timeout": 60})
        shell_ids.append(j.job_id)
    engine_jobs = {}
    for t, inv in zip(TENANTS, (None, 2, None)):
        flags = {"stub": True}
        if inv:
            flags["inv_x_bound"] = inv
        engine_jobs[f"check-{t}"] = q.submit(
            f"<stub:check-{t}>", engine="device", kind="check",
            tenant=t, flags=flags)
    engine_jobs["sim-acme"] = q.submit(
        "<stub:sim>", kind="sim", tenant="acme",
        flags={"stub": True, "inv_x_bound": 2, "walkers": 32,
               "depth": 12, "num": 96, "seed": 7})
    tp = os.path.join(tmp, "SAT_TRACE.jsonl")
    save_traces(tp, stub_trace_records(n=6, depth=5, mutate=(2, 1)))
    engine_jobs["validate-blue"] = q.submit(
        "<stub:validate>", kind="validate", tenant="blue",
        flags={"stub": True, "traces": tp, "interp": True})

    t0 = time.time()
    pool = WorkerPool(
        spool, 2, devices=4, drain=True, env=subprocess_env(),
        extra_args=["--age-every", str(age_every)]).start()
    rcs = pool.wait(timeout=420)
    elapsed = time.time() - t0
    q = JobQueue(spool)
    jobs = {j.job_id: j for j in q.jobs()}

    checks = {"workers_exited_clean": rcs == [0, 0]}
    # no starvation: EVERY tenant's jobs all reached a terminal state
    per_tenant_done = {t: 0 for t in TENANTS}
    incomplete = []
    for j in jobs.values():
        if j.state in ("done", "violated"):
            per_tenant_done[j.tenant] += 1
        else:
            incomplete.append((j.job_id, j.tenant, j.state, j.reason))
    checks["every_tenant_complete"] = not incomplete
    # engine verdicts exact across the saturated queue
    checks["check_verdicts_exact"] = (
        jobs[engine_jobs["check-acme"].job_id].result["distinct"] == 16
        and jobs[engine_jobs["check-blue"].job_id].state == "violated"
        and jobs[engine_jobs["check-blue"].job_id].result["violated"]
        == "Bound"
        and jobs[engine_jobs["check-cobra"].job_id].state == "done")
    checks["sim_found_violation"] = (
        jobs[engine_jobs["sim-acme"].job_id].state == "violated")
    vres = jobs[engine_jobs["validate-blue"].job_id].result
    checks["validate_divergence_localized"] = (
        jobs[engine_jobs["validate-blue"].job_id].state == "violated"
        and vres["divergences"][0]["trace"] == "t-0002"
        and vres["divergences"][0]["step"] == 1)
    # fair interleaving: each tenant's SHELL jobs complete around the
    # global mean rank, not tenant-after-tenant (DRR at work)
    done_order = sorted(
        (jobs[jid] for jid in shell_ids),
        key=lambda j: (j.updated_ts, j.seq))
    ranks = {t: [] for t in TENANTS}
    for rank, j in enumerate(done_order):
        ranks[j.tenant].append(rank)
    n = len(done_order)
    means = {t: (sum(r) / len(r) if r else 0.0)
             for t, r in ranks.items()}
    spread = (max(means.values()) - min(means.values())) / max(1, n)
    checks["tenants_interleaved"] = spread < 0.30
    # every job claimed exactly once per attempt, by 2 real workers
    owners = set()
    exactly_once = True
    for jid, j in jobs.items():
        evs = read_journal(q.journal_path(jid))
        starts = [e for e in evs if e["event"] == "job_started"]
        if len(starts) != max(1, j.attempts):
            exactly_once = False
        owners.update(e["worker"] for e in evs
                      if e["event"] == "sched_decision")
    checks["claimed_exactly_once"] = exactly_once
    checks["both_workers_claimed"] = len(owners) == 2
    pol = FairSharePolicy(age_every=age_every)
    out["saturation"] = {
        "jobs": len(jobs), "tenants": len(TENANTS),
        "kinds": sorted({j.kind for j in jobs.values()}),
        "workers": 2, "elapsed_s": round(elapsed, 2),
        "aging_wait_bound_s": pol.max_wait_bound(0, 5),
        "tenant_mean_ranks": {t: round(m, 1)
                              for t, m in means.items()},
        "rank_spread": round(spread, 3),
        "incomplete": incomplete[:8],
        "worker_rcs": rcs, "checks": checks,
    }
    return checks


# ---------------------------------------------------------------------
# leg 3: scaling — 2 workers >= 1.6x the drain rate of 1
# ---------------------------------------------------------------------
def _drain_rate(spool, workers):
    """Jobs/second between the first claim and the last terminal
    transition, read off the spool log (startup excluded)."""
    from tpuvsr.serve.pool import WorkerPool
    from tpuvsr.service.queue import TERMINAL, JobQueue
    from tpuvsr.testing import subprocess_env
    q = _new_queue(spool)
    n = 0
    for i in range(N_SCALE):
        q.submit(f"sleep-{i:03d}", kind="shell",
                 tenant=TENANTS[i % 3],
                 flags={"argv": _sleep_argv(SCALE_SLEEP),
                        "timeout": 60})
        n += 1
    # one light thread per worker: the ratio must measure WORKER
    # scaling, not the multi-runner's thread scaling inside one
    pool = WorkerPool(spool, workers, devices=2, drain=True,
                      env=subprocess_env(),
                      extra_args=["--light-threads", "1"]).start()
    rcs = pool.wait(timeout=420)
    t_start, t_end = None, None
    # read the state records through the spool DRIVER (ISSUE 20), so
    # the same scan works whether they live in jobs.jsonl or the
    # quorum replicas
    recs, _ = q.drv.read("jobs", None)
    for rec in recs:
        if rec.get("op") != "state":
            continue
        if rec["state"] == "running":
            ts = rec.get("ts")
            t_start = ts if t_start is None else min(t_start, ts)
        if rec["state"] in TERMINAL:
            ts = rec.get("ts")
            t_end = ts if t_end is None else max(t_end, ts)
    q.refresh()
    done = sum(1 for j in q.jobs() if j.state == "done")
    if done != n or rcs != [0] * workers or not t_start or not t_end:
        return 0.0, {"done": done, "rcs": rcs}
    return n / max(1e-6, t_end - t_start), {"done": done, "rcs": rcs}


def demo_scaling(tmp, out):
    attempts = []
    for attempt in range(2):
        r1, d1 = _drain_rate(
            os.path.join(tmp, f"spool-w1-{attempt}"), 1)
        r2, d2 = _drain_rate(
            os.path.join(tmp, f"spool-w2-{attempt}"), 2)
        ratio = r2 / r1 if r1 else 0.0
        attempts.append({"rate_1w": round(r1, 2),
                         "rate_2w": round(r2, 2),
                         "ratio": round(ratio, 2),
                         "detail": {"w1": d1, "w2": d2}})
        if ratio >= 1.6:
            break       # one retry absorbs transient machine load
    checks = {"near_linear_scaling": ratio >= 1.6}
    out["scaling"] = {"jobs": N_SCALE, "sleep_s": SCALE_SLEEP,
                      **attempts[-1], "attempts": attempts,
                      "checks": checks}
    return checks


# ---------------------------------------------------------------------
# leg 4: bit-identity — multi-worker outcomes == serial drain
# ---------------------------------------------------------------------
def _submit_identity_set(q, tmp):
    from tpuvsr.testing import stub_trace_records
    from tpuvsr.validate import save_traces
    tp = os.path.join(tmp, "ID_TRACE.jsonl")
    if not os.path.exists(tp):
        save_traces(tp, stub_trace_records(n=5, depth=6,
                                           mutate=(1, 3)))
    jobs = {}
    jobs["check-viol"] = q.submit(
        "<stub:check-viol>", engine="device", tenant="acme",
        flags={"stub": True, "inv_x_bound": 2})
    jobs["check-clean"] = q.submit(
        "<stub:check-clean>", engine="device", tenant="blue",
        flags={"stub": True})
    jobs["validate"] = q.submit(
        "<stub:validate>", kind="validate", tenant="cobra",
        flags={"stub": True, "traces": tp, "interp": True})
    jobs["sim"] = q.submit(
        "<stub:sim>", kind="sim", tenant="acme",
        flags={"stub": True, "inv_x_bound": 2, "walkers": 32,
               "depth": 12, "num": 96, "seed": 7})
    jobs["shell"] = q.submit(
        "shell-id", kind="shell", tenant="blue",
        flags={"argv": _true_argv(), "timeout": 60})
    return jobs


def _outcomes(q, jobs):
    q.refresh()
    out = {}
    for label, job in jobs.items():
        j = q.get(job.job_id)
        out[label] = {"state": j.state,
                      "result": _strip_volatile(j.result),
                      "journal": _journal_projection(q, job.job_id)}
    return out


def demo_bit_identity(tmp, out):
    from tpuvsr.service.queue import JobQueue
    from tpuvsr.service.worker import Worker

    serial_spool = os.path.join(tmp, "spool-serial")
    qs = _new_queue(serial_spool)
    serial_jobs = _submit_identity_set(qs, tmp)
    Worker(qs, devices=2, owner="serial", light_threads=0).drain()
    serial = _outcomes(qs, serial_jobs)

    multi_spool = os.path.join(tmp, "spool-multi")
    qm = _new_queue(multi_spool)
    multi_jobs = _submit_identity_set(qm, tmp)
    workers = [Worker(JobQueue(multi_spool), devices=2,
                      owner=f"w{i}", light_threads=0)
               for i in range(2)]
    threads = [threading.Thread(target=w.drain) for w in workers]
    for t in threads:
        t.start()
    for t in threads:
        t.join(600)
    multi = _outcomes(qm, multi_jobs)

    checks, diffs = {}, {}
    for label in serial_jobs:
        same = serial[label] == multi[label]
        checks[f"identical_{label}"] = same
        if not same:
            diffs[label] = {"serial": serial[label],
                            "multi": multi[label]}
    out["bit_identity"] = {
        "jobs": sorted(serial_jobs),
        "serial_states": {k: v["state"] for k, v in serial.items()},
        "multi_states": {k: v["state"] for k, v in multi.items()},
        "diffs": diffs, "checks": checks,
    }
    return checks


# ---------------------------------------------------------------------
# leg 5: abuse — the hardened front door (ISSUE 18)
# ---------------------------------------------------------------------
def demo_abuse(tmp, out):
    import http.client
    from tpuvsr.obs import read_journal
    from tpuvsr.serve.guard import Guard
    from tpuvsr.serve.http import ServiceHTTP
    from tpuvsr.service.queue import JobQueue
    from tpuvsr.service.worker import Worker
    from tpuvsr.testing import STUB_DISTINCT, STUB_LEVELS

    spool = os.path.join(tmp, "spool-abuse")
    _new_queue(spool)
    with open(os.path.join(spool, "tokens.json"), "w") as f:
        json.dump({"legit": "tok-legit", "flood": "tok-flood"}, f)
    guard = Guard(spool, rate=0.5, burst=2.0)
    svc = ServiceHTTP(spool, guard=guard).start()

    def req(method, path, body=None, token=None, headers=None):
        conn = http.client.HTTPConnection("127.0.0.1", svc.port,
                                          timeout=10)
        hdrs = dict(headers or {})
        if token:
            hdrs["Authorization"] = f"Bearer {token}"
        data = None
        if body is not None:
            data = json.dumps(body).encode()
            hdrs["Content-Type"] = "application/json"
        conn.request(method, path, body=data, headers=hdrs)
        resp = conn.getresponse()
        raw = resp.read()
        try:
            doc = json.loads(raw or b"{}")
        except ValueError:
            doc = {"raw": raw.decode(errors="replace")}
        ra = resp.getheader("Retry-After")
        conn.close()
        return resp.status, doc, ra

    checks = {}
    try:
        submit = {"spec": "<stub:legit>", "engine": "device",
                  "flags": {"stub": True}}
        code, doc, _ = req("POST", "/v1/jobs", body=submit,
                           token="tok-legit")
        legit_id = doc.get("job_id")
        checks["legit_accepted"] = code == 200
        # an unauthenticated client and an oversized body bounce at
        # the door — neither ever reaches the queue
        checks["unauthenticated_401"] = req(
            "POST", "/v1/jobs", body=submit)[0] == 401
        checks["oversized_body_413"] = req(
            "POST", "/v1/jobs", body=submit, token="tok-legit",
            headers={"Content-Length": str(guard.max_body + 1)}
        )[0] == 413
        # the flood: 10 rapid submissions against a 0.5/s budget
        flood = [req("POST", "/v1/jobs",
                     body={"spec": f"SPAM-{i}", "kind": "shell",
                           "flags": {"argv": _true_argv()}},
                     token="tok-flood")
                 for i in range(10)]
        denied = [f for f in flood if f[0] == 429]
        checks["flood_throttled_429"] = len(denied) >= 7
        checks["429_carries_retry_after"] = all(f[2] for f in denied)
        # the legit tenant's verdict is untouched by the abuse
        q = JobQueue(spool)
        Worker(q, devices=1).drain()
        legit = q.get(legit_id)
        checks["legit_verdict_exact"] = (
            legit.state == "done"
            and legit.result["distinct"] == STUB_DISTINCT
            and legit.result["levels"] == STUB_LEVELS)
        # every denial journaled AND folded onto /v1/metrics
        ev = [e["event"] for e in read_journal(
            os.path.join(spool, "guard.jsonl"))]
        checks["every_denial_journaled"] = (
            ev.count("rate_limited") == len(denied)
            and "auth_denied" in ev)
        conn = http.client.HTTPConnection("127.0.0.1", svc.port,
                                          timeout=10)
        conn.request("GET", "/v1/metrics",
                     headers={"Authorization": "Bearer tok-legit"})
        resp = conn.getresponse()
        text = resp.read().decode()
        conn.close()
        checks["denials_on_metrics"] = (
            resp.status == 200
            and f"tpuvsr_rate_limited_total {len(denied)}" in text
            and "tpuvsr_auth_denied_total 1" in text)
    finally:
        svc.stop()
    out["abuse"] = {"flood_429s": len(denied),
                    "flood_codes": [f[0] for f in flood],
                    "legit_state": legit.state,
                    "checks": checks}
    return checks


def main(argv=()):
    global SPOOL_DRIVER
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--spool-driver", default=None,
                    choices=("fs", "objstore", "quorum"))
    SPOOL_DRIVER = ap.parse_args(list(argv)).spool_driver
    tmp = tempfile.mkdtemp(prefix="tpuvsr-serve-demo-")
    out = {}
    checks = {}
    try:
        for leg in (demo_lifecycle, demo_saturation, demo_scaling,
                    demo_bit_identity, demo_abuse):
            for k, v in leg(tmp, out).items():
                checks[f"{leg.__name__}.{k}"] = v
        out["checks"] = checks
        out["ok"] = all(checks.values())
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    print(json.dumps(out, indent=1, default=str))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
