"""Dispatch-service smoke: submit 3 stub jobs, drain the queue.

The ISSUE 6 acceptance drill, end to end in one process on the stub
harness (no reference mount, CPU backend, seconds):

  clean      a plain counter job — runs supervised, reaches the exact
             16-state fixpoint, state ``done``
  rejected   a spec that fails the speclint frames pass — the
             admission gate kills it at ``queued -> failed``; it never
             reaches ``running`` and costs zero device time
  preempt    a SIGTERM-style preemption (injected kill@level=2) on a
             job whose tightened invariant has a unique witness — the
             job requeues with its rescue checkpoint, resumes, and
             reports the violation with a trace BIT-IDENTICAL to an
             uninterrupted oracle run (the PR 4/5 equivalence
             contract, now holding across the dispatcher)

Every lifecycle transition must be visible in the per-job journals
(``job_submitted``/``job_admitted``/``job_started``/``job_requeued``/
``job_done`` interleaved with the engine's own events).

Prints one JSON object; exit 0 iff every expectation holds.

    python scripts/serve_demo.py
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

if __name__ == "__main__":
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    _fl = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _fl:
        os.environ["XLA_FLAGS"] = (
            _fl + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, REPO)


def main():
    from tpuvsr.obs import read_journal
    from tpuvsr.service.queue import JobQueue
    from tpuvsr.service.worker import Worker, result_summary
    from tpuvsr.testing import STUB_DISTINCT, STUB_LEVELS

    tmp = tempfile.mkdtemp(prefix="tpuvsr-serve-demo-")
    out = {"jobs": {}}
    try:
        q = JobQueue(os.path.join(tmp, "spool"))
        clean = q.submit("<stub:clean>", engine="device",
                         flags={"stub": True})
        rejected = q.submit("<stub:rejected>", engine="device",
                            flags={"stub": True, "stub_bad": True})
        preempt = q.submit("<stub:preempt>", engine="device",
                           flags={"stub": True, "inv_x_bound": 2,
                                  "inject": "kill@level=2"})
        runs = Worker(q, devices=2).drain()

        # the uninterrupted oracle for the preempted job: the same
        # tightened-invariant engine, run clean, serialized the same way
        from tpuvsr.engine.device_bfs import DeviceBFS
        from tpuvsr.testing import counter_spec, stub_model_factory
        eng = DeviceBFS(counter_spec(inv_x_bound=2),
                        model_factory=stub_model_factory(inv_x_bound=2),
                        hash_mode="full", tile_size=4,
                        fpset_capacity=1 << 8, next_capacity=1 << 6)
        preempt_oracle = result_summary(eng.run())

        checks = {}
        jc = q.get(clean.job_id)
        evs_c = [e["event"]
                 for e in read_journal(q.journal_path(clean.job_id))]
        checks["clean_done_exact_fixpoint"] = (
            jc.state == "done"
            and jc.result["distinct"] == STUB_DISTINCT
            and jc.result["levels"] == STUB_LEVELS)
        checks["clean_journal_lifecycle"] = (
            ["job_submitted", "job_admitted", "job_started"]
            == [e for e in evs_c if e.startswith("job_")][:3]
            and evs_c[-1] == "job_done")

        jr = q.get(rejected.job_id)
        evs_r = [e["event"]
                 for e in read_journal(q.journal_path(rejected.job_id))]
        checks["rejected_by_speclint"] = (
            jr.state == "failed" and jr.reason == "speclint"
            and bool((jr.result or {}).get("speclint")))
        checks["rejected_never_ran"] = (
            "job_started" not in evs_r and "run_start" not in evs_r
            and jr.attempts == 0)

        jp = q.get(preempt.job_id)
        evs_p = [e["event"]
                 for e in read_journal(q.journal_path(preempt.job_id))]
        checks["preempt_requeued_then_completed"] = (
            jp.state == "violated" and jp.attempts == 2
            and "job_requeued" in evs_p
            and "rescue_checkpoint" in evs_p)
        checks["preempt_bit_identical_to_oracle"] = (
            jp.result is not None
            and jp.result.get("violated")
            == preempt_oracle.get("violated")
            and jp.result.get("trace") == preempt_oracle.get("trace")
            and jp.result["distinct"] == preempt_oracle["distinct"])

        for job, evs in ((jc, evs_c), (jr, evs_r), (jp, evs_p)):
            out["jobs"][job.spec] = {
                "state": job.state, "attempts": job.attempts,
                "reason": job.reason, "journal_events": evs,
            }
        out["runs"] = runs
        out["stats"] = q.stats()
        out["checks"] = checks
        out["ok"] = all(checks.values())
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    print(json.dumps(out, indent=1, default=str))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
