#!/usr/bin/env python
"""Render a job's end-to-end span tree from its journal (ISSUE 17).

Every journal event of a submitted job carries the ``trace_id`` minted
at ``job_submitted`` plus a ``span_id``/``parent_span`` naming the
process segment that wrote it (service root span -> per-attempt worker
span -> per-engine-run segment), so the journal alone reconstructs the
job's whole story across the service / worker / engine hops:

    python scripts/trace_view.py SPOOL/journals/j0001-xxxx.jsonl
    python scripts/trace_view.py --spool SPOOL --job j0001-xxxx
    python scripts/trace_view.py J.jsonl --trace 3f709578dcd6457b
    python scripts/trace_view.py J.jsonl --perfetto out.json \
        [--merge profile_trace.json]

The default output is an indented span tree with per-span timing and
event rollups.  ``--perfetto`` exports Chrome/Perfetto trace-event
JSON (``ph: "X"`` duration slices per span, ``ph: "i"`` instants for
faults/violations/breaches); ``--merge`` folds the ``traceEvents`` of
an existing profiler export (a ``TPUVSR_PROFILE`` run) into the same
file, so the service-level spans and the jitted-step spans land on one
Perfetto timeline.

Stdlib only — usable against a live spool while workers run.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def load_events(path):
    """Parse a journal leniently: skip torn/garbage lines (a live
    worker may be mid-append) — the viewer is a reader, not a
    validator."""
    out = []
    try:
        with open(path) as f:
            for line in f:
                if not line.endswith("\n"):
                    break                      # torn tail
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue
                if isinstance(ev, dict) and "event" in ev:
                    out.append(ev)
    except OSError as e:
        raise SystemExit(f"trace_view: cannot read {path}: {e}")
    return out


def build_spans(events, trace_id=None):
    """Fold events into ``{span_id: span}`` for one trace.  Returns
    ``(trace_id, spans)``; events without trace keys (pre-telemetry
    journals) fold into a synthetic ``untraced`` span so old journals
    still render."""
    traces = {}
    for ev in events:
        traces.setdefault(ev.get("trace_id"), []).append(ev)
    if trace_id is None:
        # prefer the (single) real trace; fall back to untraced
        real = [t for t in traces if t]
        if len(real) > 1:
            raise SystemExit(
                "trace_view: journal holds several traces "
                f"({', '.join(sorted(real))}); pick one with --trace")
        trace_id = real[0] if real else None
    evs = traces.get(trace_id)
    if not evs:
        raise SystemExit(f"trace_view: no events for trace "
                         f"{trace_id!r}")
    spans = {}
    for ev in evs:
        sid = ev.get("span_id") or "untraced"
        s = spans.get(sid)
        if s is None:
            s = spans[sid] = {"span_id": sid, "parent": None,
                              "t0": None, "t1": None, "events": 0,
                              "kinds": {}, "run_ids": set(),
                              "marks": []}
        if ev.get("parent_span"):
            s["parent"] = ev["parent_span"]
        ts = ev.get("ts")
        if isinstance(ts, (int, float)):
            s["t0"] = ts if s["t0"] is None else min(s["t0"], ts)
            s["t1"] = ts if s["t1"] is None else max(s["t1"], ts)
        s["events"] += 1
        kind = ev["event"]
        s["kinds"][kind] = s["kinds"].get(kind, 0) + 1
        if ev.get("run_id"):
            s["run_ids"].add(ev["run_id"])
        if kind in ("fault", "violation", "hunt_violation",
                    "divergence", "slo_breach", "degrade",
                    "rescue_checkpoint"):
            s["marks"].append((ts, kind, ev))
    # orphan parents (a span referenced but never written to — e.g. a
    # worker died before its first event) become empty placeholders
    for s in list(spans.values()):
        p = s["parent"]
        if p and p not in spans:
            spans[p] = {"span_id": p, "parent": None, "t0": s["t0"],
                        "t1": s["t1"], "events": 0, "kinds": {},
                        "run_ids": set(), "marks": []}
    return trace_id, spans


def _label(s):
    kinds = s["kinds"]
    if "job_started" in kinds:
        return "attempt"
    if "job_submitted" in kinds or "job_done" in kinds \
            or "sched_decision" in kinds:
        return "service"
    if "run_start" in kinds or "level_done" in kinds \
            or "sim_chunk" in kinds or "validate_chunk" in kinds:
        return "engine-run"
    return "segment"


def render_tree(trace_id, spans, out=sys.stdout):
    roots = sorted((s for s in spans.values() if not s["parent"]),
                   key=lambda s: (s["t0"] is None, s["t0"]))
    kids = {}
    for s in spans.values():
        if s["parent"]:
            kids.setdefault(s["parent"], []).append(s)
    t_base = min((s["t0"] for s in spans.values()
                  if s["t0"] is not None), default=0.0)
    print(f"trace {trace_id}", file=out)

    def walk(s, depth):
        dur = ((s["t1"] - s["t0"])
               if s["t0"] is not None and s["t1"] is not None else None)
        rel = (s["t0"] - t_base) if s["t0"] is not None else None
        top = ", ".join(
            f"{k}x{n}" if n > 1 else k
            for k, n in sorted(s["kinds"].items(),
                               key=lambda kv: -kv[1])[:4])
        bits = [f"{s['span_id']}", f"[{_label(s)}]"]
        if rel is not None:
            bits.append(f"+{rel:.3f}s")
        if dur is not None:
            bits.append(f"{dur:.3f}s")
        bits.append(f"{s['events']} ev" + (f" ({top})" if top else ""))
        print("  " * depth + "- " + "  ".join(bits), file=out)
        for ts, kind, ev in sorted(s["marks"],
                                   key=lambda m: m[0] or 0):
            what = ev.get("what") or ev.get("name") or \
                ev.get("kind") or ""
            print("  " * (depth + 1) + f"! {kind} {what}".rstrip(),
                  file=out)
        for kid in sorted(kids.get(s["span_id"], []),
                          key=lambda k: (k["t0"] is None, k["t0"])):
            walk(kid, depth + 1)

    for r in roots:
        walk(r, 1)


def perfetto_events(trace_id, spans):
    """Chrome/Perfetto trace-event rows: one ``X`` slice per span,
    ``i`` instants for the notable marks.  ``tid`` is a small stable
    integer per span (sorted order), ``pid`` 1 — the profiler merge
    keeps its own pids so both land on one timeline."""
    rows = []
    order = {sid: i + 1 for i, sid in enumerate(sorted(spans))}
    for sid, s in sorted(spans.items()):
        if s["t0"] is None:
            continue
        dur = max(0.0, (s["t1"] or s["t0"]) - s["t0"])
        rows.append({
            "name": f"{_label(s)} {sid}", "cat": "tpuvsr",
            "ph": "X", "ts": s["t0"] * 1e6,
            "dur": max(1.0, dur * 1e6),
            "pid": 1, "tid": order[sid],
            "args": {"trace_id": trace_id, "span_id": sid,
                     "parent_span": s["parent"],
                     "events": s["events"],
                     "run_ids": sorted(s["run_ids"])}})
        for ts, kind, ev in s["marks"]:
            if ts is None:
                continue
            rows.append({
                "name": kind, "cat": "tpuvsr", "ph": "i",
                "ts": ts * 1e6, "pid": 1, "tid": order[sid],
                "s": "t",
                "args": {k: v for k, v in ev.items()
                         if k not in ("ts",)}})
    return rows


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="render a job's span tree from its journal")
    ap.add_argument("journal", nargs="?", default=None,
                    help="path to a journal .jsonl (or use "
                         "--spool/--job)")
    ap.add_argument("--spool", default=None)
    ap.add_argument("--job", default=None)
    ap.add_argument("--trace", default=None,
                    help="trace id to render when the journal holds "
                         "several")
    ap.add_argument("--perfetto", default=None, metavar="OUT.json",
                    help="export Chrome/Perfetto trace-event JSON")
    ap.add_argument("--merge", default=None, metavar="PROFILE.json",
                    help="fold an existing trace-event file (a "
                         "TPUVSR_PROFILE export) into --perfetto's "
                         "output")
    args = ap.parse_args(argv)
    path = args.journal
    if path is None:
        if not (args.spool and args.job):
            ap.error("give a JOURNAL path, or --spool and --job")
        path = os.path.join(args.spool, "journals",
                            f"{args.job}.jsonl")
    events = load_events(path)
    if not events:
        raise SystemExit(f"trace_view: {path} holds no events")
    trace_id, spans = build_spans(events, trace_id=args.trace)
    render_tree(trace_id, spans)
    if args.perfetto:
        rows = perfetto_events(trace_id, spans)
        if args.merge:
            try:
                with open(args.merge) as f:
                    prof = json.load(f)
            except (OSError, ValueError) as e:
                raise SystemExit(
                    f"trace_view: cannot merge {args.merge}: {e}")
            rows.extend(prof.get("traceEvents", prof)
                        if isinstance(prof, dict) else prof)
        with open(args.perfetto, "w") as f:
            json.dump({"traceEvents": rows,
                       "displayTimeUnit": "ms"}, f)
        print(f"perfetto export: {args.perfetto} "
              f"({len(rows)} event(s))", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
