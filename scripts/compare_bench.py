#!/usr/bin/env python
"""Diff two metrics-schema JSON files and gate on throughput
regression — the check future perf PRs cite (ISSUE 2 satellite).

    python scripts/compare_bench.py BASELINE.json CANDIDATE.json \
        [--max-regression PCT] [--metric NAME]

Accepts any of:
  * a tpuvsr-metrics/1 document (the CLI's -metrics dump, or
    CheckResult.metrics embedded anywhere);
  * a bench.py RESULT line (BENCH_*.json) — uses its embedded
    "metrics" document when present, else the legacy top-level
    "value" (distinct states/sec) field.

Exit codes: 0 = candidate within tolerance, 1 = regression beyond
--max-regression percent, 2 = inputs unusable.  Phase-timer and
counter deltas are printed for context either way.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

METRICS_SCHEMA = "tpuvsr-metrics/1"
TELEMETRY_SCHEMA = "tpuvsr-telemetry/1"


def load(path):
    with open(path) as f:
        return json.load(f)


def find_metrics(doc):
    """The tpuvsr-metrics/1 document inside `doc`, or None."""
    if not isinstance(doc, dict):
        return None
    if doc.get("schema") == METRICS_SCHEMA:
        return doc
    m = doc.get("metrics")
    if isinstance(m, dict) and m.get("schema") == METRICS_SCHEMA:
        return m
    return None


def throughput(doc, metric):
    """(value, source_description) for the gated metric."""
    if not isinstance(doc, dict):
        return None, None
    m = find_metrics(doc)
    if m is not None:
        g = m.get("gauges", {})
        if metric in g:
            return float(g[metric]), f"gauges.{metric}"
        # derivable fallback for distinct_per_s
        if metric == "distinct_per_s" and m.get("elapsed_s"):
            d = m.get("distinct")
            if d is not None:
                return d / float(m["elapsed_s"]), "distinct/elapsed_s"
    if metric == "distinct_per_s" and "value" in doc:
        # legacy bench.py RESULT line: value IS distinct states/sec
        return float(doc["value"]), "legacy bench value"
    return None, None


def fmt_delta(base, cand):
    if base in (0, None):
        return "n/a"
    return f"{100.0 * (cand - base) / base:+.1f}%"


def sim_stats(doc):
    """Simulation throughput of a document (ISSUE 7): ``(walks_per_s,
    walkers, split_enabled)`` or ``(None, None, None)``.  Reads the
    round doc's ``sim_scale`` attachment / top-level ``sim_*`` keys,
    a raw ``sim_scale.json``, or a fleet metrics doc's
    ``gauges.walks_per_s``."""
    if not isinstance(doc, dict):
        return None, None, None
    if isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]
    sc = doc.get("sim_scale") if isinstance(doc.get("sim_scale"),
                                            dict) else None
    if sc is None and "walks_per_s" in doc:
        sc = doc
    if sc is not None and sc.get("walks_per_s") is not None:
        return (float(sc["walks_per_s"]), sc.get("walkers"),
                sc.get("split_enabled"))
    if doc.get("sim_walks_per_s") is not None:
        return (float(doc["sim_walks_per_s"]), doc.get("sim_walkers"),
                doc.get("sim_split_enabled"))
    m = find_metrics(doc)
    if m is not None and "walks_per_s" in m.get("gauges", {}):
        return (float(m["gauges"]["walks_per_s"]),
                m["gauges"].get("walkers"), None)
    return None, None, None


def gate_sim(base_doc, cand_doc, max_regression):
    """The walks/s regression gate: 0 ok/advisory/absent, 1 on a
    regression beyond tolerance at COMPARABLE walker counts (a
    cross-walker-count or cross-split-mode drop measures a different
    fleet configuration — advisory, like pipeline depth)."""
    base, bw, bs = sim_stats(base_doc)
    cand, cw, cs = sim_stats(cand_doc)
    if base is None or cand is None:
        return 0
    print(f"walks_per_s: baseline {base:.1f} -> candidate {cand:.1f}"
          f"  [{fmt_delta(base, cand)}]")
    advisory = False
    if bw is not None and cw is not None and bw != cw:
        advisory = True
        print(f"  walkers: {bw} -> {cw} (different fleet sizes — "
              f"comparison is advisory)")
    if bs is not None and cs is not None and bs != cs:
        advisory = True
        print(f"  split_enabled: {bs} -> {cs} (different splitting "
              f"modes — comparison is advisory)")
    if base > 0 and cand < base * (1.0 - max_regression / 100.0):
        if advisory:
            print(f"compare_bench: walks/s drop beyond "
                  f"{max_regression:.1f}% tolerance, but the fleets "
                  f"differ — advisory, not a regression",
                  file=sys.stderr)
            return 0
        print(f"compare_bench: walks/s REGRESSION beyond "
              f"{max_regression:.1f}% tolerance", file=sys.stderr)
        return 1
    return 0


def validate_stats(doc):
    """Trace-validation throughput of a document (ISSUE 8):
    ``(traces_per_s, batch, backend)`` or ``(None, None, None)``.
    Reads the round doc's ``validate_demo`` attachment / top-level
    ``validate_*`` keys, a raw ``validate_demo.json``, or a validator
    metrics doc's ``gauges.traces_per_s``."""
    if not isinstance(doc, dict):
        return None, None, None
    if isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]
    vd = doc.get("validate_demo") \
        if isinstance(doc.get("validate_demo"), dict) else None
    if vd is None and "traces_per_s" in doc:
        vd = doc
    if vd is not None and vd.get("traces_per_s") is not None:
        return (float(vd["traces_per_s"]), vd.get("batch"),
                vd.get("backend"))
    if doc.get("validate_traces_per_s") is not None:
        return (float(doc["validate_traces_per_s"]),
                doc.get("validate_batch"), doc.get("backend"))
    m = find_metrics(doc)
    if m is not None and "traces_per_s" in m.get("gauges", {}):
        return (float(m["gauges"]["traces_per_s"]),
                m["gauges"].get("validate_batch"), None)
    return None, None, None


def gate_validate(base_doc, cand_doc, max_regression):
    """The traces/s regression gate (ISSUE 8): 0 ok/advisory/absent,
    1 on a regression beyond tolerance on the SAME backend and batch
    shape (a cross-backend or cross-batch drop measures a different
    machine/configuration — advisory, like walks/s across fleet
    sizes)."""
    base, bb, bk = validate_stats(base_doc)
    cand, cb, ck = validate_stats(cand_doc)
    if base is None or cand is None:
        return 0
    print(f"traces_per_s: baseline {base:.1f} -> candidate "
          f"{cand:.1f}  [{fmt_delta(base, cand)}]")
    advisory = False
    if bk is not None and ck is not None and \
            str(bk).startswith("cpu") != str(ck).startswith("cpu"):
        advisory = True
        print(f"  backend: {bk} -> {ck} (different backends — "
              f"comparison is advisory)")
    if bb is not None and cb is not None and bb != cb:
        advisory = True
        print(f"  batch: {bb} -> {cb} (different round sizes — "
              f"comparison is advisory)")
    if base > 0 and cand < base * (1.0 - max_regression / 100.0):
        if advisory:
            print(f"compare_bench: traces/s drop beyond "
                  f"{max_regression:.1f}% tolerance, but the "
                  f"configurations differ — advisory, not a "
                  f"regression", file=sys.stderr)
            return 0
        print(f"compare_bench: traces/s REGRESSION beyond "
              f"{max_regression:.1f}% tolerance", file=sys.stderr)
        return 1
    return 0


def gate_pack(base_doc, cand_doc, max_regression):
    """The frontier bytes/state regression gate (ISSUE 9): 0
    ok/advisory/absent, 1 when the candidate's at-rest frontier row
    GREW beyond tolerance (bytes/state is a cost — the gate direction
    is inverted vs the throughput gates).  A pack_ratio mismatch
    between the documents (packing toggled, or a different codec
    layout entirely) measures different formats, not a regression —
    advisory, like pipeline depth."""
    bm, cm = find_metrics(base_doc), find_metrics(cand_doc)
    if not (bm and cm):
        return 0
    b = bm.get("gauges", {}).get("frontier_bytes_per_state")
    c = cm.get("gauges", {}).get("frontier_bytes_per_state")
    if b is None or c is None:
        return 0
    print(f"frontier_bytes_per_state: baseline {b} -> candidate {c}  "
          f"[{fmt_delta(b, c)}]")
    br = bm.get("gauges", {}).get("pack_ratio")
    cr = cm.get("gauges", {}).get("pack_ratio")
    if br != cr:
        print(f"  pack_ratio: {br} -> {cr} (different state formats "
              f"— comparison is advisory)")
        return 0
    if b > 0 and c > b * (1.0 + max_regression / 100.0):
        print(f"compare_bench: frontier_bytes_per_state GREW beyond "
              f"{max_regression:.1f}% tolerance", file=sys.stderr)
        return 1
    return 0


def gate_symmetry(base_doc, cand_doc, max_regression):
    """The orbit-reduction regression gate (ISSUE 11): 0
    ok/advisory/absent, 1 when — at matching symmetry modes — the
    candidate's ``orbit_ratio`` (generated / distinct-after-canon)
    DROPPED beyond tolerance or its distinct-state count GREW beyond
    tolerance: either means the canonicalization pass stopped folding
    orbits it used to fold.  A ``symmetry_perms`` mismatch between the
    documents (symmetry toggled, or a different declared group)
    measures different reductions, not a regression — advisory, like
    pipeline depth."""
    bm, cm = find_metrics(base_doc), find_metrics(cand_doc)
    if not (bm and cm):
        return 0
    bg, cg = bm.get("gauges", {}), cm.get("gauges", {})
    b, c = bg.get("orbit_ratio"), cg.get("orbit_ratio")
    if b is None or c is None:
        return 0
    print(f"orbit_ratio: baseline {b} -> candidate {c}  "
          f"[{fmt_delta(b, c)}]")
    bp, cp = bg.get("symmetry_perms"), cg.get("symmetry_perms")
    if bp != cp:
        print(f"  symmetry_perms: {bp} -> {cp} (different symmetry "
              f"reductions — comparison is advisory)")
        return 0
    if not bp or bp <= 1:
        # symmetry off (or undeclared) in BOTH documents: orbit_ratio
        # is then just the plain generated/distinct dedup ratio — a
        # legitimate exploration change moves it, no orbit fold to
        # regress.  Informational only
        print("  symmetry off in both documents — orbit gate not "
              "applicable")
        return 0
    rc = 0
    if b > 0 and c < b * (1.0 - max_regression / 100.0):
        print(f"compare_bench: orbit_ratio REGRESSION beyond "
              f"{max_regression:.1f}% tolerance", file=sys.stderr)
        rc = 1
    bd = bm.get("distinct", base_doc.get("distinct"))
    cd = cm.get("distinct", cand_doc.get("distinct"))
    # only COMPLETED runs have comparable distinct counts: on a
    # time/state-budget-bound pin a FASTER candidate explores more
    # states inside the budget — growth there is the improvement, not
    # an orbit-fold regression (bench.py guards its A/B the same way)
    complete = (bm.get("error") is None and cm.get("error") is None)
    if complete and bd and cd and \
            cd > bd * (1.0 + max_regression / 100.0):
        print(f"compare_bench: distinct states GREW beyond "
              f"{max_regression:.1f}% tolerance at the same symmetry "
              f"mode (the orbit fold regressed)", file=sys.stderr)
        rc = 1
    return rc


def gate_por(base_doc, cand_doc, max_regression):
    """The partial-order-reduction regression gate (ISSUE 16): 0
    ok/advisory/absent, 1 when — at matching por modes — the
    candidate's ``por_cut_ratio`` (generated kept / generated full
    under the ample filter; lower is better) GREW beyond tolerance:
    the static independence facts or the ample filter stopped cutting
    interleavings they used to cut.  A por-mode mismatch (the gauge
    present in only one document, or different eligible-action
    counts) measures different explorations — advisory, like the
    symmetry and commit mismatches."""
    bm, cm = find_metrics(base_doc), find_metrics(cand_doc)
    if not (bm and cm):
        return 0
    bg, cg = bm.get("gauges", {}), cm.get("gauges", {})
    b, c = bg.get("por_cut_ratio"), cg.get("por_cut_ratio")
    if b is None and c is None:
        return 0
    if b is None or c is None:
        print(f"  por_cut_ratio: {b} -> {c} (POR toggled between the "
              f"documents — comparison is advisory)")
        return 0
    print(f"por_cut_ratio: baseline {b} -> candidate {c}  "
          f"[{fmt_delta(b, c)}]")
    be = bg.get("por_eligible_actions")
    ce = cg.get("por_eligible_actions")
    if be != ce:
        print(f"  por_eligible_actions: {be} -> {ce} (different "
              f"ample filters — comparison is advisory)")
        return 0
    if not be:
        print("  no eligible actions in either document — por gate "
              "not applicable")
        return 0
    # cut ratio is a cost: growth beyond tolerance means the
    # reduction regressed (gate direction inverted, like bytes/state)
    if b > 0 and c > b * (1.0 + max_regression / 100.0):
        print(f"compare_bench: por_cut_ratio REGRESSION beyond "
              f"{max_regression:.1f}% tolerance (the ample-set "
              f"reduction weakened)", file=sys.stderr)
        return 1
    return 0


def telemetry_snapshot(doc):
    """The embedded tpuvsr-telemetry/1 snapshot inside `doc`, or
    None (bench.py rounds embed one under "telemetry" since
    ISSUE 17)."""
    if not isinstance(doc, dict):
        return None
    if isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]
    t = doc.get("telemetry")
    if isinstance(t, dict) and t.get("schema") == TELEMETRY_SCHEMA:
        return t
    return None


#: the synthesized journal the fold-determinism drill replays: one
#: job's full service story (submit -> drr pop -> start -> engine run
#: crossing a window boundary -> fault/retry -> done) plus a pool
#: heartbeat/respawn pair — enough to touch every fold family
_DRILL_JOB = [
    {"event": "job_submitted", "ts": 100.0, "run_id": "svc-submit",
     "job_id": "j0001-drill", "spec": "s.tla", "engine": "device",
     "tenant": "acme", "trace_id": "feedfacefeedface",
     "span_id": "rfeedface"},
    {"event": "sched_decision", "ts": 100.4, "run_id": "svc",
     "job_id": "j0001-drill", "tenant": "acme", "policy": "drr",
     "weight": 2, "deficit": 1.5, "priority": 0, "aged_priority": 0,
     "waited_s": 0.4, "worker": "w0"},
    {"event": "job_started", "ts": 100.5, "run_id": "svc",
     "job_id": "j0001-drill", "attempt": 1, "devices": 1},
    {"event": "run_start", "ts": 100.6, "run_id": "r1",
     "schema": "tpuvsr-journal/1", "engine": "device",
     "module": "Drill", "backend": "cpu", "resumed": False},
    {"event": "level_done", "ts": 101.0, "run_id": "r1", "depth": 1,
     "frontier": 3, "distinct": 4, "generated": 6, "elapsed_s": 0.4},
    {"event": "fault", "ts": 104.0, "run_id": "r1", "what": "oom",
     "site": "level", "elapsed_s": 3.4},
    {"event": "retry", "ts": 104.1, "run_id": "r1", "attempt": 1,
     "backoff_s": 0.0, "elapsed_s": 3.5},
    {"event": "level_done", "ts": 111.0, "run_id": "r1", "depth": 2,
     "frontier": 5, "distinct": 9, "generated": 14,
     "elapsed_s": 10.4},
    {"event": "run_end", "ts": 111.4, "run_id": "r1", "ok": True,
     "elapsed_s": 10.8, "distinct": 9, "generated": 14},
    {"event": "job_done", "ts": 111.5, "run_id": "svc",
     "job_id": "j0001-drill", "state": "done", "elapsed_s": 11.0},
]

_DRILL_POOL = [
    {"event": "worker_heartbeat", "ts": 101.5, "run_id": "pool",
     "job_id": "j0001-drill", "worker": "w0"},
    {"event": "worker_respawn", "ts": 112.0, "run_id": "pool",
     "worker": "w1", "attempt": 1, "rc": 1},
]


def gate_telemetry(base_doc, cand_doc, max_regression):
    """The telemetry fold-determinism gate (ISSUE 17): 0 ok/absent,
    1 when the streamed journal aggregator's fold stopped being a
    pure function of the journal bytes.  Drill: replay a synthesized
    spool through two fresh aggregators AND an incremental
    (poll, append, poll) one — all three snapshots must be
    IDENTICAL, or restart reconvergence is broken.  Runs only when a
    document embeds a tpuvsr-telemetry/1 snapshot (bench.py rounds
    since ISSUE 17).  Embedded counter drift between the documents
    prints as advisory context — fleet composition differences are
    not regressions."""
    bt = telemetry_snapshot(base_doc)
    ct = telemetry_snapshot(cand_doc)
    if bt is None and ct is None:
        return 0
    if bt and ct:
        bc, cc = bt.get("counters", {}), ct.get("counters", {})
        for k in sorted(set(bc) | set(cc)):
            b, c = bc.get(k, 0), cc.get(k, 0)
            if b or c:
                print(f"  telemetry.{k}: {b} -> {c} (advisory — "
                      f"fleet composition, not a regression)")
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        from tpuvsr.obs.telemetry import TelemetryAggregator
    except Exception as e:  # noqa: BLE001 — advisory outside the repo
        print(f"  telemetry gate skipped (cannot import the "
              f"aggregator: {e})")
        return 0
    with tempfile.TemporaryDirectory(
            prefix="tpuvsr-telemetry-gate-") as tmp:
        jdir = os.path.join(tmp, "journals")
        os.makedirs(jdir)
        jp = os.path.join(jdir, "j0001-drill.jsonl")
        half = len(_DRILL_JOB) // 2
        with open(jp, "w") as f:
            for ev in _DRILL_JOB[:half]:
                f.write(json.dumps(ev) + "\n")
        inc = TelemetryAggregator(tmp, journal_breaches=False)
        inc.poll()                      # mid-stream fold, then resume
        with open(jp, "a") as f:
            for ev in _DRILL_JOB[half:]:
                f.write(json.dumps(ev) + "\n")
        with open(os.path.join(tmp, "pool.jsonl"), "w") as f:
            for ev in _DRILL_POOL:
                f.write(json.dumps(ev) + "\n")
        inc.poll()
        a = TelemetryAggregator(tmp, journal_breaches=False)
        a.poll()
        b = TelemetryAggregator(tmp, journal_breaches=False)
        b.poll()
        s_inc, s_a, s_b = inc.snapshot(), a.snapshot(), b.snapshot()
    if s_a == s_b == s_inc and s_a["events"] == len(_DRILL_JOB) + \
            len(_DRILL_POOL):
        print(f"  telemetry fold: deterministic (fresh == fresh == "
              f"incremental over {s_a['events']} events)")
        return 0
    print("compare_bench: telemetry fold NONDETERMINISM — the same "
          "journal bytes produced different folds (restart "
          "reconvergence is broken)", file=sys.stderr)
    return 1


def guard_stats(doc):
    """Front-door health of a document (ISSUE 18):
    ``(reject_per_s, limiter, counters)`` or ``(None, None, None)``.
    Reads the round doc's lifted ``guard_reject_per_s`` /
    ``guard_limiter`` keys plus the ``rate_limited`` /
    ``breaker_trips`` counters (top-level or inside the embedded
    telemetry snapshot's ``guard`` section)."""
    if not isinstance(doc, dict):
        return None, None, None
    if isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]
    r = doc.get("guard_reject_per_s")
    t = telemetry_snapshot(doc) or {}
    g = t.get("guard") if isinstance(t.get("guard"), dict) else {}
    if r is None and not g:
        return None, None, None
    counters = {
        "rate_limited": (doc.get("rate_limited")
                         if doc.get("rate_limited") is not None
                         else g.get("rate_limited")),
        "breaker_trips": (doc.get("breaker_trips")
                          if doc.get("breaker_trips") is not None
                          else g.get("breaker_trips")),
    }
    return (float(r) if r is not None else None,
            doc.get("guard_limiter"), counters)


def gate_guard(base_doc, cand_doc, max_regression):
    """The front-door rejection-rate gate (ISSUE 18): 0
    ok/advisory/absent, 1 when — at matching limiter configs — the
    candidate's ``guard_reject_per_s`` DROPPED beyond tolerance.
    Every 429/fast-fail must stay cheaper than the work it refuses,
    or the rate limiter becomes a DoS amplifier.  A limiter-config
    mismatch between the documents (different rate/burst/breaker
    thresholds) measures a different admission policy — advisory,
    like pipeline depth.  ``rate_limited`` / ``breaker_trips`` drift
    prints as context (abuse-drill composition, never a
    regression)."""
    base, blim, bc = guard_stats(base_doc)
    cand, clim, cc = guard_stats(cand_doc)
    if bc and cc:
        for k in ("rate_limited", "breaker_trips"):
            b, c = bc.get(k), cc.get(k)
            if b or c:
                print(f"  guard.{k}: {b} -> {c} (advisory — abuse-"
                      f"drill composition, not a regression)")
    if base is None or cand is None:
        return 0
    print(f"guard_reject_per_s: baseline {base:.1f} -> candidate "
          f"{cand:.1f}  [{fmt_delta(base, cand)}]")
    if blim is not None and clim is not None and blim != clim:
        print(f"  guard_limiter: {blim} -> {clim} (different "
              f"admission policies — comparison is advisory)")
        return 0
    if base > 0 and cand < base * (1.0 - max_regression / 100.0):
        print(f"compare_bench: guard rejection-rate REGRESSION "
              f"beyond {max_regression:.1f}% tolerance "
              f"(fast-fail path slowed down)", file=sys.stderr)
        return 1
    return 0


def spool_stats(doc):
    """Spool data-plane health of a document (ISSUE 20): the
    per-driver op-rate dict ``{driver: {appends_per_s, claims_per_s,
    fold_ms}}`` from the round doc's embedded ``spool`` section, or
    None."""
    if not isinstance(doc, dict):
        return None
    if isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]
    s = doc.get("spool")
    if not isinstance(s, dict):
        return None
    out = {k: v for k, v in s.items()
           if isinstance(v, dict) and "appends_per_s" in v}
    return out or None


def gate_spool(base_doc, cand_doc, max_regression):
    """The spool-driver op-rate gate (ISSUE 20): 0 ok/advisory/
    absent, 1 when — at a MATCHING driver — the candidate's append
    or claim rate dropped beyond tolerance, or its fold latency
    grew beyond it.  The data plane's control path (every job
    transition is an append; every claim is a conditional put) must
    not regress underneath the engines.  Drivers present in only one
    document, and cross-driver spreads (quorum pays W-replica fsyncs
    per append), are advisory."""
    base, cand = spool_stats(base_doc), spool_stats(cand_doc)
    if base is None or cand is None:
        return 0
    rc = 0
    tol = 1.0 - max_regression / 100.0
    for drv in sorted(base):
        if drv not in cand:
            print(f"  spool.{drv}: only in baseline (advisory)")
            continue
        b, c = base[drv], cand[drv]
        for key in ("appends_per_s", "claims_per_s"):
            bv, cv = b.get(key), c.get(key)
            if bv is None or cv is None:
                continue
            print(f"spool.{drv}.{key}: baseline {bv:.1f} -> "
                  f"candidate {cv:.1f}  [{fmt_delta(bv, cv)}]")
            if bv > 0 and cv < bv * tol:
                print(f"compare_bench: spool {drv} {key} "
                      f"REGRESSION beyond {max_regression:.1f}% "
                      f"tolerance (data-plane control path slowed "
                      f"down)", file=sys.stderr)
                rc = 1
        bf, cf = b.get("fold_ms"), c.get("fold_ms")
        if bf is not None and cf is not None:
            print(f"spool.{drv}.fold_ms: baseline {bf:.2f} -> "
                  f"candidate {cf:.2f}")
            if bf > 0 and cf > bf / max(tol, 1e-9):
                print(f"compare_bench: spool {drv} fold latency "
                      f"REGRESSION beyond {max_regression:.1f}% "
                      f"tolerance", file=sys.stderr)
                rc = 1
    for drv in sorted(set(cand) - set(base)):
        print(f"  spool.{drv}: only in candidate (advisory)")
    return rc


def liveness_stats(doc):
    """Liveness-path health of a document (ISSUE 15):
    ``(edges_per_s, check_s, mode, overhead)`` or all-None.  Reads
    the round doc's ``liveness_speedup`` attachment / lifted
    top-level keys, a raw ``liveness_speedup.json``, or a liveness
    metrics doc's gauges."""
    if not isinstance(doc, dict):
        return None, None, None, None
    if isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]
    ls = doc.get("liveness_speedup") \
        if isinstance(doc.get("liveness_speedup"), dict) else None
    if ls is None and "edges_per_s" in doc:
        ls = doc
    if ls is not None and ls.get("edges_per_s") is not None:
        # bench.py lifts the headline under liveness_-prefixed names
        # (check_s/mode are too generic at the round-doc top level)
        return (float(ls["edges_per_s"]),
                ls.get("check_s", ls.get("liveness_check_s")),
                ls.get("mode", ls.get("liveness_mode")),
                ls.get("graph_overhead_ratio"))
    m = find_metrics(doc)
    if m is not None and "edges_per_s" in m.get("gauges", {}):
        g = m["gauges"]
        return (float(g["edges_per_s"]), g.get("check_s"),
                g.get("graph_mode"), g.get("graph_overhead_ratio"))
    return None, None, None, None


def gate_liveness(base_doc, cand_doc, max_regression):
    """The liveness regression gate (ISSUE 15): 0 ok/advisory/absent,
    1 when — at matching graph-construction modes — the candidate's
    ``edges_per_s`` DROPPED or its ``check_s`` GREW beyond tolerance
    (check_s is a cost: the gate direction inverts, like bytes/state).
    A mode mismatch (streamed vs two-pass docs) measures different
    construction paths — advisory, like pipeline depth."""
    be, bc, bm_, _bo = liveness_stats(base_doc)
    ce, cc, cm_, _co = liveness_stats(cand_doc)
    if be is None or ce is None:
        return 0
    print(f"edges_per_s: baseline {be:.1f} -> candidate {ce:.1f}"
          f"  [{fmt_delta(be, ce)}]")
    advisory = False
    if bm_ is not None and cm_ is not None and bm_ != cm_:
        advisory = True
        print(f"  liveness mode: {bm_} -> {cm_} (different graph-"
              f"construction paths — comparison is advisory)")
    rc = 0
    if be > 0 and ce < be * (1.0 - max_regression / 100.0):
        if advisory:
            print(f"compare_bench: edges/s drop beyond "
                  f"{max_regression:.1f}% tolerance, but the modes "
                  f"differ — advisory, not a regression",
                  file=sys.stderr)
        else:
            print(f"compare_bench: edges/s REGRESSION beyond "
                  f"{max_regression:.1f}% tolerance", file=sys.stderr)
            rc = 1
    if bc is not None and cc is not None:
        print(f"liveness check_s: baseline {bc} -> candidate {cc}"
              f"  [{fmt_delta(bc, cc)}]")
        if bc > 0 and cc > bc * (1.0 + max_regression / 100.0) \
                and not advisory:
            print(f"compare_bench: liveness check_s GREW beyond "
                  f"{max_regression:.1f}% tolerance", file=sys.stderr)
            rc = 1
    return rc


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("baseline")
    ap.add_argument("candidate")
    ap.add_argument("--max-regression", type=float, default=10.0,
                    metavar="PCT",
                    help="fail when the metric drops more than PCT%% "
                         "below baseline (default 10)")
    ap.add_argument("--metric", default="distinct_per_s",
                    help="gauge to gate on (default distinct_per_s)")
    args = ap.parse_args(argv)

    try:
        base_doc, cand_doc = load(args.baseline), load(args.candidate)
    except (OSError, ValueError) as e:
        print(f"compare_bench: cannot load inputs: {e}",
              file=sys.stderr)
        return 2
    base, bsrc = throughput(base_doc, args.metric)
    cand, csrc = throughput(cand_doc, args.metric)
    if base is None or cand is None:
        print(f"compare_bench: metric {args.metric!r} not found "
              f"(baseline: {bsrc}, candidate: {csrc})", file=sys.stderr)
        return 2

    print(f"{args.metric}: baseline {base:.1f} ({bsrc}) -> "
          f"candidate {cand:.1f} ({csrc})  [{fmt_delta(base, cand)}]")

    # pipeline-depth mismatch (ISSUE 4): a -pipeline 1 doc vs a
    # -pipeline 2 doc measures a different dispatch regime, not a
    # regression — downgrade any verdict to advisory.  Mesh-size
    # mismatch (ISSUE 5) likewise: a supervised sharded round that
    # degraded to a smaller mesh (or resharded a snapshot) measures
    # different hardware, not a code regression
    bm, cm = find_metrics(base_doc), find_metrics(cand_doc)
    pipe_mismatch = False
    mesh_mismatch = False
    commit_mismatch = False
    occ_regressed = False
    if bm and cm:
        bp = bm.get("gauges", {}).get("pipeline_depth")
        cp = cm.get("gauges", {}).get("pipeline_depth")
        if bp is not None and cp is not None and bp != cp:
            pipe_mismatch = True
            print(f"  pipeline_depth: {bp} -> {cp} (different dispatch"
                  f" windows — comparison is advisory)")
        bmesh = bm.get("gauges", {}).get("mesh_devices")
        cmesh = cm.get("gauges", {}).get("mesh_devices")
        if bmesh is not None and cmesh is not None and bmesh != cmesh:
            mesh_mismatch = True
            print(f"  mesh_devices: {bmesh} -> {cmesh} (different "
                  f"mesh sizes — comparison is advisory)")
        # commit-mode mismatch (ISSUE 10): fused vs per-action docs
        # measure different level-kernel bodies — advisory, like
        # pipeline depth (the two are bit-identical in RESULTS, so
        # only throughput comparisons are affected)
        bc = (base_doc.get("commit")
              or bm.get("gauges", {}).get("commit_mode"))
        cc = (cand_doc.get("commit")
              or cm.get("gauges", {}).get("commit_mode"))
        if bc is not None and cc is not None and bc != cc:
            commit_mismatch = True
            print(f"  commit: {bc} -> {cc} (different level-kernel "
                  f"commit modes — comparison is advisory)")
        # bounds-tightening mismatch (ISSUE 13): a bounds-off (ratio
        # 1.0) doc vs a tightened one measures different at-rest
        # representations — advisory, like pipeline depth (results
        # are bit-identical; bench's bounds_off A/B leg gates
        # counts_identical)
        br = (base_doc.get("bound_tightening_ratio")
              or bm.get("gauges", {}).get("bound_tightening_ratio"))
        cr = (cand_doc.get("bound_tightening_ratio")
              or cm.get("gauges", {}).get("bound_tightening_ratio"))
        if br is not None and cr is not None and br != cr:
            print(f"  bound_tightening_ratio: {br} -> {cr} "
                  f"(different bounds-pass tightening — comparison "
                  f"is advisory)")
        # occupancy regression gate (ISSUE 10): the fraction of expand
        # lanes doing real work dropping means the exact-count packing
        # regressed (caps ballooned past the observed need)
        bo = bm.get("gauges", {}).get("occupancy")
        co = cm.get("gauges", {}).get("occupancy")
        if bo and co:
            print(f"  occupancy: {bo} -> {co} "
                  f"({fmt_delta(bo, co)})")
            # flagged here, reported with the common exit below so the
            # rest of the comparison context still prints
            occ_regressed = (not commit_mismatch and
                             co < bo * (1.0 - args.max_regression
                                        / 100.0))

    # context: phase-timer and counter drift between the documents
    if bm and cm:
        for section in ("phases", "counters"):
            keys = sorted(set(bm.get(section, {}))
                          | set(cm.get(section, {})))
            for k in keys:
                b = bm.get(section, {}).get(k, 0)
                c = cm.get(section, {}).get(k, 0)
                if b or c:
                    print(f"  {section}.{k}: {b} -> {c} "
                          f"({fmt_delta(b, c)})")
        bl, cl = bm.get("levels") or [], cm.get("levels") or []
        if bl and cl and (len(bl) != len(cl)
                          or bl[-1].get("distinct")
                          != cl[-1].get("distinct")):
            print(f"  trajectory: {len(bl)} levels / "
                  f"{bl[-1].get('distinct')} distinct -> {len(cl)} / "
                  f"{cl[-1].get('distinct')} (NOT the same exploration"
                  f" — throughput comparison may be meaningless)")

    # simulation throughput rides the same gate (ISSUE 7): walks/s
    # regressions fail, cross-walker-count comparisons are advisory
    if occ_regressed:
        print(f"compare_bench: occupancy REGRESSION beyond "
              f"{args.max_regression:.1f}% tolerance", file=sys.stderr)
    sim_rc = gate_sim(base_doc, cand_doc, args.max_regression)
    # trace-validation throughput likewise (ISSUE 8): traces/s
    # regressions fail, cross-backend/batch comparisons are advisory.
    # Always evaluated (not short-circuited) so BOTH regressions are
    # reported in one run
    val_rc = gate_validate(base_doc, cand_doc, args.max_regression)
    # at-rest frontier bytes ride the gate too (ISSUE 9): bytes/state
    # growth fails, cross-format comparisons are advisory
    pack_rc = gate_pack(base_doc, cand_doc, args.max_regression)
    # orbit reduction likewise (ISSUE 11): orbit_ratio drops and
    # distinct-state growth fail at matching symmetry modes;
    # symmetry-mode mismatches are advisory
    sym_rc = gate_symmetry(base_doc, cand_doc, args.max_regression)
    # the liveness path likewise (ISSUE 15): edges/s drops and
    # check_s growth fail at matching graph-construction modes;
    # streamed-vs-two-pass mismatches are advisory
    liv_rc = gate_liveness(base_doc, cand_doc, args.max_regression)
    # the ample-set reduction likewise (ISSUE 16): por_cut_ratio
    # growth fails at matching por modes; on/off mismatches are
    # advisory
    por_rc = gate_por(base_doc, cand_doc, args.max_regression)
    # the telemetry fold likewise (ISSUE 17): same journals must
    # produce an identical fold — determinism regressions fail,
    # embedded fleet-counter drift is advisory
    tel_rc = gate_telemetry(base_doc, cand_doc, args.max_regression)
    # the hardened front door likewise (ISSUE 18): the guard's
    # fast-fail rejection rate drops fail at matching limiter
    # configs; policy mismatches and abuse-drill counter drift are
    # advisory
    grd_rc = gate_guard(base_doc, cand_doc, args.max_regression)
    # the spool data plane likewise (ISSUE 20): append/claim rate
    # drops and fold-latency growth fail at matching drivers;
    # cross-driver spreads are advisory
    spl_rc = gate_spool(base_doc, cand_doc, args.max_regression)
    sim_rc = (sim_rc or val_rc or pack_rc or sym_rc or liv_rc
              or por_rc or tel_rc or grd_rc or spl_rc
              or (1 if occ_regressed else 0))

    if base > 0 and cand < base * (1.0 - args.max_regression / 100.0):
        if pipe_mismatch or mesh_mismatch or commit_mismatch:
            what = ("pipeline depths" if pipe_mismatch
                    else "mesh sizes" if mesh_mismatch
                    else "commit modes")
            print(f"compare_bench: drop beyond "
                  f"{args.max_regression:.1f}% tolerance, but the "
                  f"documents ran different {what} — "
                  f"advisory, not a regression", file=sys.stderr)
            return sim_rc
        print(f"compare_bench: REGRESSION beyond "
              f"{args.max_regression:.1f}% tolerance", file=sys.stderr)
        return 1
    if sim_rc:
        return sim_rc
    print("compare_bench: within tolerance")
    return 0


if __name__ == "__main__":
    sys.exit(main())
