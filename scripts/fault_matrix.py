"""Fault-injection smoke matrix on the inline stub spec (ISSUE 3).

Runs every resilience path end to end, in-process, through the REAL
engine loops driven by the stub kernel (tpuvsr/testing.py) — no
reference mount, no TPU, seconds on the CPU backend:

  oom-degrade        injected RESOURCE_EXHAUSTED at a mid level ->
                     supervisor halves the tile, retries from the
                     snapshot, completes with the exact fixpoint
  oom-paged-fallback repeated OOMs at the tile floor -> hbm -> paged
                     engine fallback, still the exact fixpoint
  kill-rescue        injected SIGTERM under a PreemptionGuard ->
                     rescue checkpoint at the level boundary,
                     Preempted raised; -recover reproduces the
                     uninterrupted run's counts exactly
  pack-kill-rescue   same kill with the packed frontier ON (ISSUE 9):
                     the rescue snapshot stores DENSE planes, and both
                     a packed and a -pack off engine resume it to the
                     exact fixpoint
  corrupt-ckpt       crash-corrupted snapshot write (payload truncated,
                     .old kept) -> load_checkpoint falls back to .old
                     and the resumed run still reaches the fixpoint
  garble-ckpt        bit-rot snapshot write (payload bytes XOR-flipped
                     in place, size preserved — only the manifest
                     CRC32 can catch it) -> CRC verify fails, .old
                     fallback, resumed run reaches the fixpoint
  exchange-drop      transient sharded-exchange failure -> journaled
                     retry, level step re-issued, exact fixpoint
  exchange-drop-retry persistent exchange-drop:3 -> three journaled
                     retries with exponential backoff, then the level
                     step goes through; exact fixpoint (and a drop
                     count beyond the budget fails loudly)
  oom-mesh-degrade   injected OOM on a supervised SHARDED run at the
                     tile floor -> mesh shrink 4 -> 2 devices, elastic
                     resume re-hash-partitions the snapshot, exact
                     fixpoint (ISSUE 5 mesh degrade ladder)
  kill-elastic-resume injected SIGTERM on a 4-device sharded run ->
                     rescue checkpoint; resumed on a 2-device mesh ->
                     journaled reshard, exact fixpoint
  pipeline-faults    oom + kill injected into -pipeline 4 runs ->
                     the dispatch window drains, the supervisor/rescue
                     paths behave exactly as at -pipeline 1
  service-preempt-requeue SIGTERM-style kill under the DISPATCHER
                     (tpuvsr/service, ISSUE 6) -> job requeued with
                     its rescue checkpoint, reclaimed, resumed to the
                     exact fixpoint; job_* transitions journaled
  service-oom-degrade injected OOM under the dispatcher -> the
                     per-job supervisor degrades the tile inside ONE
                     job run (no requeue), exact fixpoint
  sim-oom-shrink     injected OOM inside a walker-fleet chunk
                     (ISSUE 7) -> the fleet halves its walker count
                     (degrade {what:"walkers"}), redraws the round,
                     and the trace matches the degraded-count oracle
  kill-hunt-resume   SIGTERM mid-hunt -> walker-frontier rescue
                     snapshot + Preempted; the resumed hunt's deduped
                     violation set and headline trace are
                     bit-identical to an uninterrupted oracle hunt
  kill-canon-resume  SIGTERM mid-run with symmetry canonicalization
                     ON (ISSUE 11) -> rescue snapshot recording the
                     canon spec; a -symmetry off engine REFUSES it
                     (policy error) and a symmetry-on engine resumes
                     to the exact orbit fixpoint
  kill-spill-resume  SIGTERM on a paged run spilling to DISK level
                     files (ISSUE 11, 2-row RAM budget) -> rescue
                     checkpoint; the resume reloads the frontier
                     through the tier and completes the exact fixpoint
  kill-bounds-resume SIGTERM mid-run under bounds-TIGHTENED packing
                     (ISSUE 13) -> rescue snapshot recording the
                     facts digest; tightened AND untightened (bounds
                     off) kill/resume pairs both reach the exact
                     fixpoint, and a flipped -bounds resume is
                     REFUSED (policy error)
  kill-por-resume    SIGTERM mid-run with the ample-set reduction
                     live (ISSUE 16) -> rescue snapshot recording the
                     independence facts digest; the matched resume
                     completes the exact REDUCED fixpoint, and a
                     flipped -por resume is REFUSED in both
                     directions
  kill-validate-resume  SIGTERM mid-batch on a kind="validate" job
                     (ISSUE 8) -> candidate-frontier rescue at the
                     committed chunk boundary, preempt-requeue through
                     the queue, and the resumed attempt's divergence
                     report is bit-identical to an undisturbed oracle
                     job's
  kill-aggregator-mid-tail  SIGKILL the telemetry aggregator mid-tail
                     (ISSUE 17) -> the spool stays fully servable: the
                     torn breach-journal tail is held back, a fresh
                     aggregator refolds from byte 0, and two fresh
                     folds are bit-identical (the fold is a pure
                     function of the journal bytes)
  kill-worker-mid-event  SIGKILL a worker mid-run under
                     TPUVSR_JOURNAL_FSYNC=1 (ISSUE 17) -> the dead
                     worker's journal is a valid prefix (every
                     complete line parses), the live aggregator folds
                     it, the survivor resumes the job, and the
                     incremental fold reconverges exactly with a
                     from-scratch fold
  flood-rate-limit   a flooding tenant hammers the hardened HTTP
                     front door (ISSUE 18) -> bounded 429s with
                     Retry-After, every denial journaled; a legit
                     tenant's job still completes with the exact
                     stub fixpoint
  breaker-crash-loop a crash-looping (tenant, spec) trips the
                     circuit breaker after K failures -> later
                     submissions fail fast with reason breaker-open
                     (no subprocess spawned); a clean run after the
                     cooldown closes it via the half-open probe —
                     both transitions journaled, telemetry fold
                     restart-convergent
  slow-loris-reap    a client that sends half a request line and
                     stalls is reaped by the per-connection read
                     timeout; the service stays fully responsive
  host-death-failover  an ENTIRE host (pool parent + worker, one
                     process) is SIGKILLed mid-sharded-job and its
                     local checkpoint dir dies with it (ISSUE 20) ->
                     the survivor host's recover_stale sweeps the dead
                     host's claims by its stale LEASE, restores the
                     rescue from the quorum driver's blob store, and
                     resumes to a verdict bit-identical to an oracle's
  spool-replica-loss one replica of the quorum spool deleted
                     mid-drain (ISSUE 20) -> the service is unaffected
                     (appends still reach write quorum), replica_lost
                     journaled; recreating the dir heals via
                     anti-entropy — replica_rejoin journaled, replica
                     logs byte-identical
  zombie-fence       a recovered-then-revived worker tries to commit
                     its stale terminal state (ISSUE 20) -> the
                     claim-epoch fence rejects the append
                     (FencedError, journaled ``fence``); the
                     successor's verdict stands: exactly-once
  kill-liveness-resume  SIGTERM mid-graph-build on a STREAMED temporal
                     run (ISSUE 15: edges flowing out of the fused
                     commit) -> rescue snapshot carrying gid column +
                     edge rows + retained levels; the resumed run's
                     CSR, verdict and lasso trace are bit-identical
                     to an uninterrupted oracle's

Prints one JSON object; exit 0 iff every scenario passed.  Run by
tests/test_resilience.py under tier-1 and standalone:

    python scripts/fault_matrix.py
"""

import json
import os
import shutil
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

if __name__ == "__main__":
    # standalone: force the virtual-device CPU backend BEFORE any jax
    # import (under pytest, tests/conftest.py already did this)
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    _fl = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _fl:
        os.environ["XLA_FLAGS"] = (
            _fl + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, REPO)


def _oracle():
    from tpuvsr.testing import STUB_DISTINCT, STUB_LEVELS
    return {"distinct": STUB_DISTINCT, "levels": STUB_LEVELS}


def _factory(spec):
    from tpuvsr.testing import stub_engine_factory
    return stub_engine_factory(spec)


def _events(path):
    from tpuvsr.obs import read_journal
    return [e["event"] for e in read_journal(path)]


def scenario_oom_degrade(tmp):
    ORACLE = _oracle()
    from tpuvsr.resilience import faults
    from tpuvsr.resilience.supervisor import Supervisor
    from tpuvsr.testing import counter_spec
    spec = counter_spec()
    jp = os.path.join(tmp, "oom.jsonl")
    faults.install("oom@level=3")
    try:
        sup = Supervisor(spec, checkpoint_path=os.path.join(tmp, "ck"),
                         journal_path=jp, engine_factory=_factory(spec),
                         tile_size=4, min_tile=2, backoff_base=0.0,
                         sleep=lambda s: None)
        res = sup.run()
    finally:
        faults.clear()
    ev = _events(jp)
    return {
        "ok": (res.ok and res.distinct_states == ORACLE["distinct"]
               and res.levels == ORACLE["levels"] and sup.attempts == 2
               and ("tile", 4, 2) in sup.degrades
               and "fault" in ev and "retry" in ev and "degrade" in ev),
        "attempts": sup.attempts, "degrades": sup.degrades,
        "distinct": res.distinct_states,
    }


def scenario_oom_paged_fallback(tmp):
    ORACLE = _oracle()
    from tpuvsr.resilience import faults
    from tpuvsr.resilience.supervisor import Supervisor
    from tpuvsr.testing import counter_spec
    spec = counter_spec()
    jp = os.path.join(tmp, "paged.jsonl")
    # tile 4 with floor 4: the first OOM exhausts the halving ladder
    # and falls straight to the paged engine; later OOMs retry there
    faults.install("oom@level=2,oom@level=3,oom@level=4")
    try:
        sup = Supervisor(spec, checkpoint_path=os.path.join(tmp, "ck"),
                         journal_path=jp, engine_factory=_factory(spec),
                         tile_size=4, min_tile=4, backoff_base=0.0,
                         sleep=lambda s: None)
        res = sup.run()
    finally:
        faults.clear()
    return {
        "ok": (res.ok and res.distinct_states == ORACLE["distinct"]
               and res.levels == ORACLE["levels"]
               and sup.kind == "paged"
               and ("engine", "device", "paged") in sup.degrades),
        "attempts": sup.attempts, "engine": sup.kind,
        "distinct": res.distinct_states,
    }


def scenario_kill_rescue(tmp):
    ORACLE = _oracle()
    from tpuvsr.obs import RunObserver
    from tpuvsr.resilience import faults
    from tpuvsr.resilience.supervisor import (Preempted,
                                              PreemptionGuard)
    from tpuvsr.testing import stub_device_engine
    ck = os.path.join(tmp, "kill-ck")
    jp = os.path.join(tmp, "kill.jsonl")
    faults.install("kill@level=3")
    preempted = None
    try:
        with PreemptionGuard():
            try:
                stub_device_engine().run(
                    checkpoint_path=ck,
                    obs=RunObserver(journal_path=jp))
            except Preempted as p:
                preempted = p
    finally:
        faults.clear()
    if preempted is None:
        return {"ok": False, "why": "no Preempted raised"}
    res2 = stub_device_engine().run(resume_from=ck)
    ev = _events(jp)
    return {
        "ok": (preempted.depth == 3 and res2.ok
               and res2.distinct_states == ORACLE["distinct"]
               and res2.levels == ORACLE["levels"]
               and "rescue_checkpoint" in ev and "fault" in ev),
        "rescue_depth": preempted.depth,
        "distinct_after_recover": res2.distinct_states,
    }


def scenario_pack_kill_rescue(tmp):
    """ISSUE 9 satellite: kill mid-run with the packed frontier ON ->
    rescue checkpoint (stored DENSE, the interchange format), then BOTH
    a packed and a dense engine resume it to the exact fixpoint — the
    packed at-rest representation is invisible across the rescue
    seam."""
    ORACLE = _oracle()
    from tpuvsr.obs import RunObserver
    from tpuvsr.resilience import faults
    from tpuvsr.resilience.supervisor import (Preempted,
                                              PreemptionGuard)
    from tpuvsr.testing import stub_device_engine
    ck = os.path.join(tmp, "pack-ck")
    jp = os.path.join(tmp, "pack.jsonl")
    faults.install("kill@level=3")
    preempted = None
    try:
        with PreemptionGuard():
            try:
                eng = stub_device_engine()      # pack defaults ON
                assert eng._pk is not None
                eng.run(checkpoint_path=ck,
                        obs=RunObserver(journal_path=jp))
            except Preempted as p:
                preempted = p
    finally:
        faults.clear()
    if preempted is None:
        return {"ok": False, "why": "no Preempted raised"}
    res_packed = stub_device_engine().run(resume_from=ck)
    res_dense = stub_device_engine(pack=False).run(resume_from=ck)
    from tpuvsr.obs import read_journal
    starts = [e for e in read_journal(jp) if e["event"] == "run_start"]
    return {
        "ok": (preempted.depth == 3
               and res_packed.ok and res_dense.ok
               and res_packed.distinct_states == ORACLE["distinct"]
               and res_dense.distinct_states == ORACLE["distinct"]
               and res_packed.levels == ORACLE["levels"]
               and res_dense.levels == ORACLE["levels"]
               and all(e.get("pack") for e in starts)),
        "rescue_depth": preempted.depth,
        "distinct_packed": res_packed.distinct_states,
        "distinct_dense": res_dense.distinct_states,
    }


def scenario_kill_fused_commit_resume(tmp):
    """ISSUE 10 satellite: kill mid-chunk with packing AND the fused
    (occupancy-packed single-insert) commit on -> rescue checkpoint,
    then a fused resume AND a per-action resume both reach the exact
    uninterrupted fixpoint — the three-stage commit restructure is
    invisible across the rescue seam, and the journal's run_start rows
    carry the commit key."""
    ORACLE = _oracle()
    from tpuvsr.obs import RunObserver
    from tpuvsr.resilience import faults
    from tpuvsr.resilience.supervisor import (Preempted,
                                              PreemptionGuard)
    from tpuvsr.testing import stub_device_engine
    ck = os.path.join(tmp, "fused-ck")
    jp = os.path.join(tmp, "fused.jsonl")
    faults.install("kill@level=3")
    preempted = None
    try:
        with PreemptionGuard():
            try:
                eng = stub_device_engine()      # commit defaults fused
                assert eng.commit == "fused" and eng._pk is not None
                eng.run(checkpoint_path=ck,
                        obs=RunObserver(journal_path=jp))
            except Preempted as p:
                preempted = p
    finally:
        faults.clear()
    if preempted is None:
        return {"ok": False, "why": "no Preempted raised"}
    res_fused = stub_device_engine().run(resume_from=ck)
    res_pa = stub_device_engine(
        commit="per-action").run(resume_from=ck)
    from tpuvsr.obs import read_journal
    starts = [e for e in read_journal(jp) if e["event"] == "run_start"]
    return {
        "ok": (preempted.depth == 3
               and res_fused.ok and res_pa.ok
               and res_fused.distinct_states == ORACLE["distinct"]
               and res_pa.distinct_states == ORACLE["distinct"]
               and res_fused.levels == ORACLE["levels"]
               and res_pa.levels == ORACLE["levels"]
               and all(e.get("commit") == "fused" for e in starts)),
        "rescue_depth": preempted.depth,
        "distinct_fused": res_fused.distinct_states,
        "distinct_per_action": res_pa.distinct_states,
    }


def scenario_kill_canon_resume(tmp):
    """ISSUE 11 satellite: kill mid-run with symmetry canonicalization
    ON -> rescue checkpoint recording the canon spec, then (a) a
    symmetry-on engine resumes to the exact orbit fixpoint, (b) a
    symmetry-off engine REFUSES the snapshot (policy error — the
    stored fingerprints live in the canonical space)."""
    from tpuvsr.core.values import TLAError
    from tpuvsr.obs import RunObserver, read_journal
    from tpuvsr.resilience import faults
    from tpuvsr.resilience.supervisor import (Preempted,
                                              PreemptionGuard)
    from tpuvsr.testing import SYMPAIR_ORBIT_LEVELS, SYMPAIR_ORBITS, \
        stub_sym_engine
    ck = os.path.join(tmp, "canon-ck")
    jp = os.path.join(tmp, "canon.jsonl")
    faults.install("kill@level=2")
    preempted = None
    try:
        with PreemptionGuard():
            try:
                eng = stub_sym_engine()         # symmetry auto -> ON
                assert eng._canon is not None
                eng.run(checkpoint_path=ck,
                        obs=RunObserver(journal_path=jp))
            except Preempted as p:
                preempted = p
    finally:
        faults.clear()
    if preempted is None:
        return {"ok": False, "why": "no Preempted raised"}
    refused = False
    try:
        stub_sym_engine(symmetry=False).run(resume_from=ck)
    except TLAError as e:
        refused = "symmetry canonicalization" in str(e)
    res = stub_sym_engine().run(resume_from=ck)
    starts = [e for e in read_journal(jp)
              if e["event"] == "run_start"]
    return {
        "ok": (refused and res.ok
               and res.distinct_states == SYMPAIR_ORBITS
               and res.levels == SYMPAIR_ORBIT_LEVELS
               and all(e.get("symmetry") for e in starts)),
        "rescue_depth": preempted.depth, "flip_refused": refused,
        "distinct": res.distinct_states,
    }


def scenario_kill_spill_resume(tmp):
    """ISSUE 11 satellite: kill a paged run whose frontier is spilling
    to DISK level files (2-row RAM budget) -> rescue checkpoint, then
    the resumed run reloads the frontier THROUGH the tier and
    completes the exact fixpoint."""
    ORACLE = _oracle()
    from tpuvsr.engine.paged_bfs import PagedBFS
    from tpuvsr.obs import RunObserver, read_journal
    from tpuvsr.resilience import faults
    from tpuvsr.resilience.supervisor import (Preempted,
                                              PreemptionGuard)
    from tpuvsr.testing import stub_device_engine
    ck = os.path.join(tmp, "spill-ck")
    jp = os.path.join(tmp, "spill.jsonl")
    sd = os.path.join(tmp, "spill-tier")
    faults.install("kill@level=4")
    preempted = None
    try:
        with PreemptionGuard():
            try:
                stub_device_engine(
                    cls=PagedBFS, spill_dir=sd, spill_ram_rows=2,
                    chunk_tiles=1).run(
                    checkpoint_path=ck,
                    obs=RunObserver(journal_path=jp))
            except Preempted as p:
                preempted = p
    finally:
        faults.clear()
    if preempted is None:
        return {"ok": False, "why": "no Preempted raised"}
    res = stub_device_engine(cls=PagedBFS, spill_dir=sd,
                             spill_ram_rows=2,
                             chunk_tiles=1).run(resume_from=ck)
    disk = [e for e in read_journal(jp)
            if e["event"] == "spill" and e.get("tier") == "disk"]
    return {
        "ok": (res.ok and res.distinct_states == ORACLE["distinct"]
               and res.levels == ORACLE["levels"] and len(disk) > 0),
        "rescue_depth": preempted.depth,
        "disk_spills": len(disk),
        "distinct": res.distinct_states,
    }


def scenario_kill_bounds_resume(tmp):
    """ISSUE 13 satellite: kill mid-run under bounds-TIGHTENED packing
    -> rescue checkpoint recording the facts digest; the tightened
    resume completes the exact fixpoint, a flipped -bounds resume is
    REFUSED (policy error), and an untightened (bounds-off) kill/
    resume pair is bit-identical too."""
    ORACLE = _oracle()
    from tpuvsr.core.values import TLAError
    from tpuvsr.obs import RunObserver, read_journal
    from tpuvsr.resilience import faults
    from tpuvsr.resilience.supervisor import (Preempted,
                                              PreemptionGuard)
    from tpuvsr.testing import stub_device_engine

    def kill_run(ck, jp, **kw):
        faults.install("kill@level=3")
        preempted = None
        try:
            with PreemptionGuard():
                try:
                    eng = stub_device_engine(**kw)
                    eng.run(checkpoint_path=ck,
                            obs=RunObserver(journal_path=jp))
                except Preempted as p:
                    preempted = p
        finally:
            faults.clear()
        return preempted

    ck_on = os.path.join(tmp, "bounds-on-ck")
    jp = os.path.join(tmp, "bounds.jsonl")
    p_on = kill_run(ck_on, jp)                 # bounds default ON
    if p_on is None:
        return {"ok": False, "why": "no Preempted raised (on leg)"}
    eng_on = stub_device_engine()
    assert eng_on._pk.total_bits < eng_on._pk_decl.total_bits
    res_on = eng_on.run(resume_from=ck_on)
    flipped = False
    try:
        stub_device_engine(bounds=False).run(resume_from=ck_on)
    except TLAError:
        flipped = True
    ck_off = os.path.join(tmp, "bounds-off-ck")
    p_off = kill_run(ck_off, os.path.join(tmp, "bounds-off.jsonl"),
                     bounds=False)
    if p_off is None:
        return {"ok": False, "why": "no Preempted raised (off leg)"}
    res_off = stub_device_engine(bounds=False).run(resume_from=ck_off)
    starts = [e for e in read_journal(jp)
              if e["event"] == "run_start"]
    return {
        "ok": (p_on.depth == 3 and res_on.ok and res_off.ok
               and res_on.distinct_states == ORACLE["distinct"]
               and res_off.distinct_states == ORACLE["distinct"]
               and res_on.levels == ORACLE["levels"]
               and res_off.levels == ORACLE["levels"]
               and flipped
               and all((e.get("bounds") or {}).get("tightened")
                       for e in starts)),
        "rescue_depth": p_on.depth,
        "distinct_tightened": res_on.distinct_states,
        "distinct_untightened": res_off.distinct_states,
        "flip_refused": flipped,
    }


def scenario_kill_por_resume(tmp):
    """ISSUE 16 satellite: kill mid-run with the ample-set reduction
    live -> rescue checkpoint recording the independence facts digest;
    the matched resume completes the exact REDUCED fixpoint
    bit-identically, and a flipped -por resume is REFUSED in both
    directions (on-snapshot -> off engine, off-snapshot -> on
    engine)."""
    from tpuvsr.core.values import TLAError
    from tpuvsr.obs import RunObserver, read_journal
    from tpuvsr.resilience import faults
    from tpuvsr.resilience.supervisor import (Preempted,
                                              PreemptionGuard)
    from tpuvsr.testing import (POR_STUB_DISTINCT, POR_STUB_LEVELS,
                                counter_spec, stub_device_engine)

    def kill_run(ck, jp, **kw):
        faults.install("kill@level=3")
        preempted = None
        try:
            with PreemptionGuard():
                try:
                    eng = stub_device_engine(
                        spec=counter_spec(inv_free=True), **kw)
                    eng.run(checkpoint_path=ck,
                            obs=RunObserver(journal_path=jp))
                except Preempted as p:
                    preempted = p
        finally:
            faults.clear()
        return preempted

    ck_on = os.path.join(tmp, "por-on-ck")
    jp = os.path.join(tmp, "por.jsonl")
    p_on = kill_run(ck_on, jp, por="on")
    if p_on is None:
        return {"ok": False, "why": "no Preempted raised (on leg)"}
    res_on = stub_device_engine(spec=counter_spec(inv_free=True),
                                por="on").run(resume_from=ck_on)
    flip_off = False
    try:
        stub_device_engine(spec=counter_spec(inv_free=True)).run(
            resume_from=ck_on)
    except TLAError:
        flip_off = True
    ck_off = os.path.join(tmp, "por-off-ck")
    p_off = kill_run(ck_off, os.path.join(tmp, "por-off.jsonl"))
    if p_off is None:
        return {"ok": False, "why": "no Preempted raised (off leg)"}
    flip_on = False
    try:
        stub_device_engine(spec=counter_spec(inv_free=True),
                           por="on").run(resume_from=ck_off)
    except TLAError:
        flip_on = True
    starts = [e for e in read_journal(jp)
              if e["event"] == "run_start"]
    return {
        "ok": (p_on.depth == 3 and res_on.ok
               and res_on.distinct_states == POR_STUB_DISTINCT
               and res_on.levels == POR_STUB_LEVELS
               and flip_off and flip_on
               and all((e.get("por") or {}).get("eligible_actions")
                       == 2 for e in starts)),
        "rescue_depth": p_on.depth,
        "distinct_reduced": res_on.distinct_states,
        "flip_off_refused": flip_off,
        "flip_on_refused": flip_on,
    }


def scenario_corrupt_ckpt(tmp):
    ORACLE = _oracle()
    from tpuvsr.resilience import faults
    from tpuvsr.testing import stub_device_engine
    ck = os.path.join(tmp, "corrupt-ck")
    # every-level checkpoints; the level-3 write is crash-corrupted
    # (frontier.npz truncated, the level-2 snapshot kept as .old)
    faults.install("corrupt-ckpt:frontier.npz@level=3")
    try:
        res1 = stub_device_engine().run(max_depth=3,
                                        checkpoint_path=ck)
    finally:
        faults.clear()
    old_ok = os.path.isdir(ck + ".old")
    res2 = stub_device_engine().run(resume_from=ck)
    return {
        "ok": (bool(res1.error) and old_ok and res2.ok
               and res2.distinct_states == ORACLE["distinct"]
               and res2.levels == ORACLE["levels"]),
        "old_present": old_ok,
        "distinct_after_recover": res2.distinct_states,
    }


def scenario_garble_ckpt(tmp):
    ORACLE = _oracle()
    from tpuvsr.resilience import faults
    from tpuvsr.testing import stub_device_engine
    ck = os.path.join(tmp, "garble-ck")
    # every-level checkpoints; the level-3 write is bit-rotted in place
    # (fpset.npz garbled, size preserved — only the CRC catches it)
    faults.install("garble-ckpt:fpset.npz@level=3")
    try:
        res1 = stub_device_engine().run(max_depth=3,
                                        checkpoint_path=ck)
    finally:
        faults.clear()
    old_ok = os.path.isdir(ck + ".old")
    # the garbled payload is np.load-able garbage of the right size:
    # only the manifest CRC32 distinguishes it from a good snapshot
    logs = []
    res2 = stub_device_engine().run(resume_from=ck,
                                    log=logs.append)
    crc_seen = any("CRC32 mismatch" in m for m in logs)
    return {
        "ok": (bool(res1.error) and old_ok and crc_seen and res2.ok
               and res2.distinct_states == ORACLE["distinct"]
               and res2.levels == ORACLE["levels"]),
        "old_present": old_ok, "crc_detected": crc_seen,
        "distinct_after_recover": res2.distinct_states,
    }


def scenario_pipeline_faults(tmp):
    """oom + kill landing while a -pipeline 4 window is in flight:
    the drain-and-replay contract must leave the supervisor/rescue
    paths bit-identical to the synchronous engine."""
    ORACLE = _oracle()
    from tpuvsr.obs import RunObserver
    from tpuvsr.resilience import faults
    from tpuvsr.resilience.supervisor import (Preempted,
                                              PreemptionGuard,
                                              Supervisor)
    from tpuvsr.testing import counter_spec, stub_device_engine, \
        stub_engine_factory
    spec = counter_spec()
    # oom mid-run under the supervisor, window depth 4
    faults.install("oom@level=3")
    try:
        sup = Supervisor(spec, checkpoint_path=os.path.join(tmp, "ck"),
                         engine_factory=stub_engine_factory(
                             spec, pipeline=4),
                         tile_size=4, min_tile=2, backoff_base=0.0,
                         sleep=lambda s: None)
        res = sup.run()
    finally:
        faults.clear()
    oom_ok = (res.ok and res.distinct_states == ORACLE["distinct"]
              and res.levels == ORACLE["levels"])
    # kill mid-run, window depth 4: rescue at the (drained) boundary
    ck = os.path.join(tmp, "kill-ck")
    jp = os.path.join(tmp, "kill.jsonl")
    faults.install("kill@level=3")
    preempted = None
    try:
        with PreemptionGuard():
            try:
                stub_device_engine(pipeline=4).run(
                    checkpoint_path=ck,
                    obs=RunObserver(journal_path=jp))
            except Preempted as p:
                preempted = p
    finally:
        faults.clear()
    res2 = stub_device_engine(pipeline=4).run(resume_from=ck) \
        if preempted else None
    kill_ok = (preempted is not None and res2 is not None and res2.ok
               and res2.distinct_states == ORACLE["distinct"]
               and res2.levels == ORACLE["levels"])
    return {"ok": oom_ok and kill_ok, "oom_ok": oom_ok,
            "kill_ok": kill_ok}


def scenario_exchange_drop(tmp):
    ORACLE = _oracle()
    import jax
    if len(jax.devices()) < 2:
        return {"ok": True, "skipped": "needs 2 virtual devices"}
    import numpy as np
    from jax.sharding import Mesh
    from tpuvsr.obs import RunObserver, read_journal
    from tpuvsr.parallel.sharded_bfs import ShardedBFS
    from tpuvsr.resilience import faults
    from tpuvsr.testing import counter_spec, stub_model_factory
    jp = os.path.join(tmp, "exchange.jsonl")
    mesh = Mesh(np.array(jax.devices()[:2]), ("d",))
    faults.install("exchange-drop@shard=0@level=2")
    try:
        eng = ShardedBFS(counter_spec(), mesh, tile=4, bucket_cap=64,
                         next_capacity=1 << 6, fpset_capacity=1 << 8,
                         model_factory=stub_model_factory())
        res = eng.run(obs=RunObserver(journal_path=jp))
    finally:
        faults.clear()
    events = read_journal(jp)
    kinds = [e["event"] for e in events]
    return {
        "ok": (res.ok and res.distinct_states == ORACLE["distinct"]
               and res.levels == ORACLE["levels"]
               and "fault" in kinds and "retry" in kinds),
        "distinct": res.distinct_states,
    }


def scenario_exchange_drop_retry(tmp):
    """Persistent exchange-drop:3 (a flaky ICI link): three journaled
    retries with exponential backoff, then the level step goes
    through — the exact fixpoint either way (ISSUE 5)."""
    ORACLE = _oracle()
    import jax
    if len(jax.devices()) < 2:
        return {"ok": True, "skipped": "needs 2 virtual devices"}
    from tpuvsr.obs import RunObserver, read_journal
    from tpuvsr.resilience import faults
    from tpuvsr.testing import stub_sharded_engine
    jp = os.path.join(tmp, "xretry.jsonl")
    faults.install("exchange-drop:3@shard=0@level=2")
    try:
        eng = stub_sharded_engine(n_devices=2, sleep=lambda s: None)
        res = eng.run(obs=RunObserver(journal_path=jp))
    finally:
        faults.clear()
    retries = [e for e in read_journal(jp) if e["event"] == "retry"]
    backoffs = [e["backoff_s"] for e in retries]
    return {
        "ok": (res.ok and res.distinct_states == ORACLE["distinct"]
               and res.levels == ORACLE["levels"]
               and [e["attempt"] for e in retries] == [1, 2, 3]
               and all(e.get("what") == "exchange" for e in retries)
               and backoffs == sorted(backoffs)),
        "retries": [(e["attempt"], e["backoff_s"]) for e in retries],
        "distinct": res.distinct_states,
    }


def scenario_oom_mesh_degrade(tmp):
    """Supervised sharded run, injected OOM at the tile floor: the
    mesh degrade ladder shrinks 4 -> 2 devices and the elastic resume
    re-hash-partitions the snapshot — exact fixpoint (ISSUE 5)."""
    ORACLE = _oracle()
    import jax
    if len(jax.devices()) < 4:
        return {"ok": True, "skipped": "needs 4 virtual devices"}
    from tpuvsr.obs import read_journal
    from tpuvsr.resilience import faults
    from tpuvsr.resilience.supervisor import Supervisor
    from tpuvsr.testing import counter_spec, stub_sharded_factory
    spec = counter_spec()
    jp = os.path.join(tmp, "mesh.jsonl")
    faults.install("oom@level=3")
    try:
        sup = Supervisor(spec, engine="sharded", mesh_devices=4,
                         checkpoint_path=os.path.join(tmp, "ck"),
                         journal_path=jp,
                         engine_factory=stub_sharded_factory(spec),
                         tile_size=4, min_tile=4, backoff_base=0.0,
                         sleep=lambda s: None)
        res = sup.run()
    finally:
        faults.clear()
    ev = [e["event"] for e in read_journal(jp)]
    return {
        "ok": (res.ok and res.distinct_states == ORACLE["distinct"]
               and res.levels == ORACLE["levels"]
               and ("mesh", 4, 2) in sup.degrades
               and sup.summary()["resharded_from"] == 4
               and "degrade" in ev and "retry" in ev
               and "reshard" in ev),
        "degrades": sup.degrades, "mesh_devices": sup.n_dev,
        "distinct": res.distinct_states,
    }


def scenario_kill_elastic_resume(tmp):
    """SIGTERM on a 4-device sharded run -> rescue checkpoint; the
    resume comes back on HALF the mesh (a lost pod slice) and the
    snapshot is re-hash-partitioned at load — exact fixpoint, reshard
    journaled (ISSUE 5)."""
    ORACLE = _oracle()
    import jax
    if len(jax.devices()) < 4:
        return {"ok": True, "skipped": "needs 4 virtual devices"}
    from tpuvsr.obs import RunObserver, read_journal
    from tpuvsr.resilience import faults
    from tpuvsr.resilience.supervisor import (Preempted,
                                              PreemptionGuard)
    from tpuvsr.testing import stub_sharded_engine
    ck = os.path.join(tmp, "kill-ck")
    jp = os.path.join(tmp, "kill.jsonl")
    faults.install("kill@level=3")
    preempted = None
    try:
        with PreemptionGuard():
            try:
                stub_sharded_engine(n_devices=4).run(
                    checkpoint_path=ck,
                    obs=RunObserver(journal_path=jp))
            except Preempted as p:
                preempted = p
    finally:
        faults.clear()
    if preempted is None:
        return {"ok": False, "why": "no Preempted raised"}
    eng2 = stub_sharded_engine(n_devices=2)
    res2 = eng2.run(resume_from=ck,
                    obs=RunObserver(journal_path=jp))
    ev = [e["event"] for e in read_journal(jp)]
    return {
        "ok": (preempted.depth == 3 and res2.ok
               and res2.distinct_states == ORACLE["distinct"]
               and res2.levels == ORACLE["levels"]
               and eng2.resharded_from == 4
               and "rescue_checkpoint" in ev and "reshard" in ev),
        "rescue_depth": preempted.depth,
        "resharded_from": eng2.resharded_from,
        "distinct_after_recover": res2.distinct_states,
    }


def scenario_service_preempt_requeue(tmp):
    """A SIGTERM-style preemption UNDER THE DISPATCHER (ISSUE 6): the
    injected kill fires mid-run inside the service worker, the job is
    requeued with its rescue checkpoint attached, and the same drain
    claims it again and resumes to the exact fixpoint — every
    transition visible in the job's own journal."""
    ORACLE = _oracle()
    from tpuvsr.obs import read_journal
    from tpuvsr.service.queue import JobQueue
    from tpuvsr.service.worker import Worker
    q = JobQueue(os.path.join(tmp, "spool"))
    job = q.submit("<stub>", engine="device",
                   flags={"stub": True, "inject": "kill@level=3"})
    Worker(q, devices=1).drain()
    done = q.get(job.job_id)
    ev = [e["event"] for e in read_journal(q.journal_path(job.job_id))]
    starts = [e for e in read_journal(q.journal_path(job.job_id))
              if e["event"] == "job_started"]
    return {
        "ok": (done.state == "done" and done.attempts == 2
               and done.result["distinct"] == ORACLE["distinct"]
               and done.result["levels"] == ORACLE["levels"]
               and "job_requeued" in ev and "rescue_checkpoint" in ev
               and "job_done" in ev and len(starts) == 2),
        "state": done.state, "attempts": done.attempts,
        "distinct": done.result["distinct"],
    }


def scenario_service_oom_degrade(tmp):
    """An injected OOM under the dispatcher: the per-job supervisor
    degrades (tile halving) INSIDE one job run — the job never leaves
    ``running``, completes with the exact fixpoint, and the degrade is
    journaled in the job's own journal (ISSUE 6)."""
    ORACLE = _oracle()
    from tpuvsr.obs import read_journal
    from tpuvsr.service.queue import JobQueue
    from tpuvsr.service.worker import Worker
    q = JobQueue(os.path.join(tmp, "spool"))
    job = q.submit("<stub>", engine="device",
                   flags={"stub": True, "inject": "oom@level=3",
                          "supervisor": {"tile_size": 4, "min_tile": 2,
                                         "backoff_base": 0.0}})
    Worker(q, devices=1).drain()
    done = q.get(job.job_id)
    ev = [e["event"] for e in read_journal(q.journal_path(job.job_id))]
    degrades = [e for e in read_journal(q.journal_path(job.job_id))
                if e["event"] == "degrade"]
    return {
        "ok": (done.state == "done" and done.attempts == 1
               and done.result["distinct"] == ORACLE["distinct"]
               and done.result["levels"] == ORACLE["levels"]
               and "fault" in ev and "retry" in ev
               and any(d["what"] == "tile" and d["from"] == 4
                       and d["to"] == 2 for d in degrades)
               and "job_requeued" not in ev),
        "state": done.state, "attempts": done.attempts,
        "degrades": [(d["what"], d["from"], d["to"]) for d in degrades],
    }


#: the kill-one-of-N dead worker: claims ONE job off the spool and
#: SIGKILLs itself (no rescue, no atexit — a genuinely dead process)
#: at the depth-2 tick, after the level-1 checkpoint has landed (the
#: unique-witness violation itself lands at depth 3 — the kill must
#: precede it)
_DOOMED_WORKER = """\
import os, signal, sys
from tpuvsr.service.queue import JobQueue
from tpuvsr.service.worker import Worker

def on_level(worker, job, depth):
    if depth >= 2:
        os.kill(os.getpid(), signal.SIGKILL)

Worker(JobQueue(sys.argv[1]), devices=1, owner="wA",
       on_level=on_level, light_threads=0).drain(max_jobs=1)
"""


def scenario_kill_one_of_n_workers(tmp):
    """ISSUE 14: N workers share one spool; one is SIGKILLed mid-job
    (dead pid, claim file left, per-level checkpoints on disk).  The
    SURVIVOR's ordinary drain loop recovers the stale claim — the
    worker-id/host-aware liveness judgment — requeues the job WITH
    the rescue snapshot, resumes it, and reports the violation with a
    trace BIT-IDENTICAL to an uninterrupted oracle.  The survivor
    also drains the dead worker's unclaimed backlog."""
    import subprocess
    from tpuvsr.engine.device_bfs import DeviceBFS
    from tpuvsr.obs import read_journal
    from tpuvsr.service.queue import JobQueue
    from tpuvsr.service.worker import Worker, result_summary
    from tpuvsr.testing import (counter_spec, stub_model_factory,
                                subprocess_env)
    spool = os.path.join(tmp, "spool")
    q = JobQueue(spool)
    doomed = q.submit("<stub:doomed>", engine="device",
                      flags={"stub": True, "inv_x_bound": 2})
    other = q.submit("<stub:other>", engine="device",
                     flags={"stub": True})
    p = subprocess.run(
        [sys.executable, "-c", _DOOMED_WORKER, spool],
        env=subprocess_env(), capture_output=True, text=True,
        timeout=300)
    killed = p.returncode in (-9, 137)
    claim_left = os.path.exists(
        os.path.join(q.claims_dir, f"{doomed.job_id}.claim"))
    # the survivor: recover_stale runs inside its ordinary drain loop
    Worker(q, devices=1, owner="wB", light_threads=0).drain()
    jd, jo = q.get(doomed.job_id), q.get(other.job_id)
    evs = read_journal(q.journal_path(doomed.job_id))
    req = [e for e in evs if e["event"] == "job_requeued"]
    workers = [e["worker"] for e in evs
               if e["event"] == "sched_decision"]
    oracle = result_summary(
        DeviceBFS(counter_spec(inv_x_bound=2),
                  model_factory=stub_model_factory(inv_x_bound=2),
                  hash_mode="full", tile_size=4,
                  fpset_capacity=1 << 8, next_capacity=1 << 6).run())
    ok = (killed and claim_left
          and jd.state == "violated" and jd.attempts == 2
          and len(req) == 1 and "worker-died" in req[0]["reason"]
          and (req[0].get("rescue") or {}).get("depth", 0) >= 1
          and jd.result["violated"] == oracle["violated"] == "Bound"
          and jd.result["trace"] == oracle["trace"]
          and jd.result["distinct"] == oracle["distinct"]
          and jo.state == "done"
          and jo.result["distinct"] == _oracle()["distinct"]
          and workers == ["wA", "wB"])
    return {
        "ok": ok, "killed_rc": p.returncode,
        "claim_left_behind": claim_left,
        "doomed": {"state": jd.state, "attempts": jd.attempts,
                   "requeue_reason": req[0]["reason"] if req else None,
                   "rescue_depth": (req[0].get("rescue") or {}).get(
                       "depth") if req else None,
                   "trace_identical": (jd.result or {}).get("trace")
                   == oracle["trace"]},
        "survivor_finished_backlog": jo.state,
        "workers_seen": workers,
    }


#: the killed telemetry aggregator: tails the spool in a tight poll
#: loop under a microscopic queue-wait SLO (so it journals
#: ``slo_breach`` lines to its own telemetry/events.jsonl), then
#: SIGKILLs itself after the first poll that folded events — offsets
#: lost, breach journal mid-life
_DOOMED_AGGREGATOR = """\
import os, signal, sys, time
from tpuvsr.obs.telemetry import TelemetryAggregator

agg = TelemetryAggregator(sys.argv[1], window_s=1.0,
                          slo={"queue_wait_p99_s": 1e-9})
while True:
    agg.poll()
    if agg.snapshot()["events"] > 0:
        os.kill(os.getpid(), signal.SIGKILL)
    time.sleep(0.05)
"""


def scenario_kill_aggregator_mid_tail(tmp):
    """ISSUE 17: the telemetry aggregator is a pure READER — SIGKILL
    it mid-tail (in-memory offsets lost, its breach journal possibly
    torn mid-append) and the spool must stay fully servable: a torn
    events.jsonl tail is held back by the \\n-holdback discipline, a
    fresh aggregator refolds from byte 0 without error, and two
    independent fresh folds are IDENTICAL (the fold is a pure
    function of the journal bytes — nothing the dead reader held
    mattered)."""
    import subprocess
    from tpuvsr.obs.telemetry import TelemetryAggregator
    from tpuvsr.service.queue import JobQueue
    from tpuvsr.service.worker import Worker
    from tpuvsr.testing import subprocess_env
    spool = os.path.join(tmp, "spool")
    q = JobQueue(spool)
    q.submit("<stub>", engine="device", flags={"stub": True})
    Worker(q, devices=1).drain()
    p = subprocess.run(
        [sys.executable, "-c", _DOOMED_AGGREGATOR, spool],
        env=subprocess_env(), capture_output=True, text=True,
        timeout=300)
    killed = p.returncode in (-9, 137)
    # simulate the worst kill point: a half-appended breach line with
    # no terminating newline left on the aggregator's own journal
    evp = os.path.join(spool, "telemetry", "events.jsonl")
    breach_lines = 0
    if os.path.exists(evp):
        with open(evp) as f:
            breach_lines = sum(1 for ln in f if ln.endswith("\n"))
        with open(evp, "a") as f:
            f.write('{"event": "slo_br')
    # more fleet activity lands AFTER the reader died
    j2 = q.submit("<stub:after>", engine="device",
                  flags={"stub": True})
    Worker(q, devices=1).drain()
    a1 = TelemetryAggregator(spool, journal_breaches=False)
    a1.poll()
    a2 = TelemetryAggregator(spool, journal_breaches=False)
    a2.poll()
    s1, s2 = a1.snapshot(), a2.snapshot()
    done = q.get(j2.job_id)
    ok = (killed and breach_lines >= 1 and s1 == s2
          and s1["counters"]["jobs_submitted"] == 2
          and s1["counters"]["slo_breaches"] >= 1
          and done.state == "done")
    return {
        "ok": ok, "killed_rc": p.returncode,
        "breach_lines_journaled": breach_lines,
        "events_folded": s1["events"],
        "slo_breaches": s1["counters"]["slo_breaches"],
        "reconverged": s1 == s2,
    }


def scenario_kill_worker_mid_event(tmp):
    """ISSUE 17: a worker SIGKILLed mid-run under
    ``TPUVSR_JOURNAL_FSYNC=1`` leaves a journal that is a valid
    prefix — every complete line parses, at most the last line is
    torn — the live aggregator folds it without error, the survivor
    recovers and finishes the job, and the killed-then-resumed
    incremental fold reconverges EXACTLY with a from-scratch fold."""
    import subprocess
    from tpuvsr.obs.telemetry import TelemetryAggregator
    from tpuvsr.service.queue import JobQueue
    from tpuvsr.service.worker import Worker
    from tpuvsr.testing import subprocess_env
    spool = os.path.join(tmp, "spool")
    q = JobQueue(spool)
    job = q.submit("<stub>", engine="device", flags={"stub": True})
    p = subprocess.run(
        [sys.executable, "-c", _DOOMED_WORKER, spool],
        env=subprocess_env({"TPUVSR_JOURNAL_FSYNC": "1"}),
        capture_output=True, text=True, timeout=300)
    killed = p.returncode in (-9, 137)
    # the dead worker's journal: every \n-terminated line is valid
    # JSON (fsync-per-event means nothing buffered was lost)
    torn, parsed = 0, []
    with open(q.journal_path(job.job_id)) as f:
        for line in f:
            if not line.endswith("\n"):
                torn += 1
                continue
            parsed.append(json.loads(line))
    mid = TelemetryAggregator(spool, journal_breaches=False)
    mid.poll()
    mid_events = mid.snapshot()["events"]
    # the survivor's ordinary drain recovers the stale claim
    Worker(q, devices=1, owner="wB", light_threads=0).drain()
    done = q.get(job.job_id)
    mid.poll()                 # the mid-kill aggregator keeps tailing
    fresh = TelemetryAggregator(spool, journal_breaches=False)
    fresh.poll()
    s_resumed, s_fresh = mid.snapshot(), fresh.snapshot()
    ok = (killed and torn <= 1 and len(parsed) >= 3
          and mid_events >= len(parsed)
          and done.state == "done"
          and s_resumed == s_fresh
          and s_fresh["counters"]["requeues"] >= 1
          and s_fresh["jobs_by_state"].get("done") == 1)
    return {
        "ok": ok, "killed_rc": p.returncode,
        "torn_lines": torn, "parsed_lines": len(parsed),
        "state": done.state,
        "incremental_fold_reconverged": s_resumed == s_fresh,
    }


def scenario_sim_oom_shrink(tmp):
    """Injected OOM inside a fleet chunk (ISSUE 7): the fleet's own
    degrade ladder halves the walker count, journals
    ``degrade {what: "walkers"}`` + ``retry``, redraws the round, and
    the run still completes — per-walk determinism makes the redraw
    exact."""
    from tpuvsr.obs import RunObserver, read_journal
    from tpuvsr.resilience import faults
    from tpuvsr.testing import stub_fleet
    jp = os.path.join(tmp, "sim-oom.jsonl")
    faults.install("oom@level=2")
    try:
        sim = stub_fleet(walkers=32, n_devices=2, inv_x_bound=2)
        res = sim.run(num=64, depth=8, seed=3,
                      obs=RunObserver(journal_path=jp))
    finally:
        faults.clear()
    # oracle at the DEGRADED walker count: the redraw must match it
    oracle = stub_fleet(walkers=16, n_devices=2, inv_x_bound=2).run(
        num=64, depth=8, seed=3)
    ev = [e["event"] for e in read_journal(jp)]
    degr = [(e["what"], e["from"], e["to"])
            for e in read_journal(jp) if e["event"] == "degrade"]
    same = (res.violated_invariant == oracle.violated_invariant
            and [(t.action_name, t.state) for t in res.trace]
            == [(t.action_name, t.state) for t in oracle.trace])
    return {
        "ok": (not res.ok and sim.walkers == 16 and same
               and ("walkers", 32, 16) in degr
               and "fault" in ev and "retry" in ev),
        "walkers": sim.walkers, "degrades": degr,
        "trace_matches_degraded_oracle": same,
    }


def scenario_kill_hunt_resume(tmp):
    """SIGTERM mid-hunt under the fleet (ISSUE 7): rescue snapshot of
    the walker frontier at the committed chunk boundary, exit-75-style
    Preempted; the resumed hunt's unique-violation set and headline
    trace are bit-identical to an uninterrupted oracle hunt."""
    from tpuvsr.obs import RunObserver, read_journal
    from tpuvsr.resilience import faults
    from tpuvsr.resilience.supervisor import (Preempted,
                                              PreemptionGuard)
    from tpuvsr.sim.hunt import run_hunt, sim_result_summary
    from tpuvsr.testing import counter_spec, stub_model_factory
    spec = counter_spec(inv_x_bound=2)
    factory = stub_model_factory(inv_x_bound=2)
    kw = dict(walkers=32, n_devices=2, depth=8, seed=5, num=64,
              chunk_steps=4, model_factory=factory)
    oracle = sim_result_summary(run_hunt(spec, **kw))
    ck = os.path.join(tmp, "hunt-ck")
    jp = os.path.join(tmp, "hunt.jsonl")
    faults.install("kill@level=1")
    preempted = None
    try:
        with PreemptionGuard():
            try:
                run_hunt(spec, checkpoint_path=ck,
                         obs=RunObserver(journal_path=jp), **kw)
            except Preempted as p:
                preempted = p
    finally:
        faults.clear()
    if preempted is None:
        return {"ok": False, "why": "no Preempted raised"}
    res2 = sim_result_summary(run_hunt(
        spec, resume_from=ck, obs=RunObserver(journal_path=jp), **kw))
    ev = [e["event"] for e in read_journal(jp)]
    return {
        "ok": (res2["violations"] == oracle["violations"]
               and res2["trace"] == oracle["trace"]
               and res2["walks"] == oracle["walks"]
               and "rescue_checkpoint" in ev and "fault" in ev
               and "sim_chunk" in ev and "hunt_violation" in ev),
        "unique_violations": len(res2["violations"]),
        "walks": res2["walks"],
    }


def scenario_kill_validate_resume(tmp):
    """SIGTERM mid-batch on a ``kind="validate"`` job (ISSUE 8): the
    batch validator rescues its committed candidate frontier at the
    chunk boundary and raises Preempted; the worker maps that to
    preempted-requeued, the next claim resumes from the rescue, and
    the final divergence report (trace id, step, enabled set) is
    bit-identical to an undisturbed oracle job's."""
    from tpuvsr.obs import read_journal
    from tpuvsr.service.queue import JobQueue
    from tpuvsr.service.worker import Worker
    from tpuvsr.testing import stub_trace_records
    from tpuvsr.validate.traces import save_traces
    q = JobQueue(os.path.join(tmp, "spool"))
    tp = os.path.join(tmp, "traces.jsonl")
    save_traces(tp, stub_trace_records(n=64, depth=6, seed=5,
                                       mutate=(40, 3)))
    flags = {"stub": True, "traces": tp, "batch": 16,
             "chunk_steps": 2}
    oracle = q.submit("<stub:v-oracle>", kind="validate",
                      flags=dict(flags))
    kill = q.submit("<stub:v-kill>", kind="validate",
                    flags=dict(flags, inject="kill@level=1"))
    Worker(q, devices=2).drain()
    jo, jk = q.get(oracle.job_id), q.get(kill.job_id)
    if jo.state != "violated" or jk.state != "violated":
        return {"ok": False, "oracle_state": jo.state,
                "kill_state": jk.state,
                "why": (jk.reason or jo.reason)}
    ev = [e["event"] for e in read_journal(q.journal_path(jk.job_id))]
    fd = jk.result["first_divergence"]
    return {
        "ok": (jk.attempts == 2
               and jk.result["divergences"] == jo.result["divergences"]
               and fd["trace"] == "t-0040" and fd["step"] == 3
               and "rescue_checkpoint" in ev and "job_requeued" in ev
               and "validate_chunk" in ev and "divergence" in ev),
        "attempts": jk.attempts,
        "divergences": len(jk.result["divergences"]),
        "traces": jk.result["traces"],
    }


def scenario_kill_liveness_resume(tmp):
    """ISSUE 15 satellite: SIGTERM-kill mid-graph-build on a STREAMED
    temporal run (the behavior graph flowing out of the fused commit)
    -> rescue snapshot carrying the gid column, the drained edge rows
    and the retained level blocks; the resumed run completes with a
    CSR, verdict and lasso trace bit-identical to an uninterrupted
    oracle's."""
    from tpuvsr.engine.device_liveness import DeviceGraph
    from tpuvsr.engine.liveness import liveness_check
    from tpuvsr.obs import RunObserver
    from tpuvsr.resilience import faults
    from tpuvsr.resilience.supervisor import (Preempted,
                                              PreemptionGuard)
    from tpuvsr.testing import (canon_csr, stub_ticker_factory,
                                ticker_spec)
    spec = ticker_spec(modulus=8)        # 16 states, 9 levels
    kw = dict(tile_size=2, chunk_tiles=1, next_capacity=16,
              fpset_capacity=1 << 8, hash_mode="full",
              model_factory=stub_ticker_factory(modulus=8))
    canon = canon_csr
    oracle = DeviceGraph(spec, mode="stream", **kw)
    r_o = liveness_check(spec, graph=oracle)

    ck = os.path.join(tmp, "liveness-ck")
    jp = os.path.join(tmp, "liveness.jsonl")
    faults.install("kill@level=4")
    preempted = None
    try:
        with PreemptionGuard():
            try:
                DeviceGraph(spec, mode="stream", checkpoint_path=ck,
                            obs=RunObserver(journal_path=jp), **kw)
            except Preempted as p:
                preempted = p
    finally:
        faults.clear()
    if preempted is None:
        return {"ok": False, "why": "no Preempted raised"}
    g2 = DeviceGraph(spec, mode="stream", resume_from=ck, **kw)
    r2 = liveness_check(spec, graph=g2)
    ev = _events(jp)

    def trace(r):
        return [(e.action_name, e.state) for e in r.trace]
    return {
        "ok": (preempted.depth == 4
               and g2.n == oracle.n
               and canon(g2) == canon(oracle)
               and all(g2.states[s] == oracle.states[s]
                       for s in range(g2.n))
               and (r2.ok, r2.property_name) == (r_o.ok,
                                                 r_o.property_name)
               and trace(r2) == trace(r_o)
               and r2.cycle_start == r_o.cycle_start
               and "rescue_checkpoint" in ev and "fault" in ev),
        "rescue_depth": preempted.depth,
        "states": g2.n,
        "edges": int(g2.csr[1].shape[0]),
        "verdict_ok": r2.ok,
    }


def scenario_flood_rate_limit(tmp):
    """ISSUE 18: a flooding tenant hammers the hardened HTTP front
    door -> the per-tenant token bucket turns the flood into bounded
    429s carrying Retry-After (every denial journaled as
    rate_limited), an unauthenticated probe bounces 401, and the
    legit tenant's job still completes with the EXACT stub
    fixpoint — abuse never changes a verdict."""
    import http.client
    ORACLE = _oracle()
    from tpuvsr.obs import read_journal
    from tpuvsr.serve.guard import Guard
    from tpuvsr.serve.http import ServiceHTTP
    from tpuvsr.service.queue import JobQueue
    from tpuvsr.service.worker import Worker
    from tpuvsr.testing import true_argv
    spool = os.path.join(tmp, "spool")
    os.makedirs(spool, exist_ok=True)
    with open(os.path.join(spool, "tokens.json"), "w") as f:
        json.dump({"legit": "tok-l", "flood": "tok-f"}, f)
    guard = Guard(spool, rate=0.5, burst=2.0)
    svc = ServiceHTTP(spool, guard=guard).start()

    def post(token, body):
        conn = http.client.HTTPConnection("127.0.0.1", svc.port,
                                          timeout=10)
        hdrs = {"Content-Type": "application/json"}
        if token:
            hdrs["Authorization"] = f"Bearer {token}"
        conn.request("POST", "/v1/jobs",
                     body=json.dumps(body).encode(), headers=hdrs)
        resp = conn.getresponse()
        doc = json.loads(resp.read() or b"{}")
        ra = resp.getheader("Retry-After")
        conn.close()
        return resp.status, doc, ra

    try:
        code, doc, _ = post("tok-l", {"spec": "<stub>",
                                      "engine": "device",
                                      "flags": {"stub": True}})
        legit_id = doc.get("job_id")
        flood = [post("tok-f", {"spec": "SPAM", "kind": "shell",
                                "flags": {"argv": true_argv()}})
                 for _ in range(10)]
        denied = [f for f in flood if f[0] == 429]
        noauth = post(None, {"spec": "X", "kind": "shell",
                             "flags": {"argv": true_argv()}})[0]
        q = JobQueue(spool)
        Worker(q, devices=1).drain()
        done = q.get(legit_id)
    finally:
        svc.stop()
    ev = [e["event"]
          for e in read_journal(os.path.join(spool, "guard.jsonl"))]
    return {
        "ok": (code == 200 and done.state == "done"
               and done.result["distinct"] == ORACLE["distinct"]
               and done.result["levels"] == ORACLE["levels"]
               and len(denied) >= 7
               and all(f[2] is not None for f in denied)
               and noauth == 401
               and ev.count("rate_limited") == len(denied)
               and "auth_denied" in ev),
        "flood_429s": len(denied), "noauth": noauth,
        "legit_state": done.state,
        "distinct": done.result["distinct"],
    }


def scenario_breaker_crash_loop(tmp):
    """ISSUE 18: a crash-looping (tenant, spec) trips the circuit
    breaker after K=2 failures -> the next submissions fail FAST with
    reason breaker-open (no subprocess spawned), a clean run after
    the cooldown closes it via the half-open probe, both transitions
    are journaled, and two fresh telemetry folds of the guard journal
    are identical (restart-convergent)."""
    import time
    from tpuvsr.obs import read_journal
    from tpuvsr.obs.telemetry import TelemetryAggregator
    from tpuvsr.serve.guard import Guard, spec_digest
    from tpuvsr.service.queue import JobQueue
    from tpuvsr.service.worker import Worker
    from tpuvsr.testing import true_argv
    spool = os.path.join(tmp, "spool")
    q = JobQueue(spool)
    guard = Guard(spool, breaker_k=2, breaker_cooldown=1.0)
    w = Worker(q, devices=1, light_threads=0, policy=None,
               owner="w-brk", guard=guard)
    fail = [sys.executable, "-c", "import sys; sys.exit(3)"]
    for i in range(4):
        q.submit("CRASH", kind="shell", tenant="a",
                 flags={"argv": fail, "timeout": 30}, job_id=f"c{i}")
    w.drain(idle_exit=True)
    jobs = {j.job_id: j for j in q.jobs()}
    digest = spec_digest("CRASH", None)
    opened = guard.breaker_state("a", digest) == "open"
    time.sleep(1.2)                # past the cooldown: half-open
    q.submit("CRASH", kind="shell", tenant="a",
             flags={"argv": true_argv(), "timeout": 30},
             job_id="probe")
    w.drain(idle_exit=True)
    closed = guard.breaker_state("a", digest) == "closed"
    ev = [e["event"]
          for e in read_journal(os.path.join(spool, "guard.jsonl"))]
    a1 = TelemetryAggregator(spool, journal_breaches=False)
    a1.poll()
    a2 = TelemetryAggregator(spool, journal_breaches=False)
    a2.poll()
    g1 = a1.snapshot()["guard"]
    g2 = a2.snapshot()["guard"]
    return {
        "ok": (jobs["c0"].reason == "rc=3"
               and jobs["c1"].reason == "rc=3"
               and jobs["c2"].reason == "breaker-open"
               and jobs["c3"].reason == "breaker-open"
               and opened and closed
               and q.get("probe").state == "done"
               and ev.count("breaker_open") == 1
               and ev.count("breaker_close") == 1
               and g1 == g2 and g1["breaker_trips"] == 1
               and g1["breaker_closes"] == 1
               and g1["open_breakers"] == []),
        "fast_fail_reasons": [jobs["c2"].reason, jobs["c3"].reason],
        "probe_state": q.get("probe").state,
        "fold_reconverged": g1 == g2,
    }


def scenario_slow_loris_reap(tmp):
    """ISSUE 18: a client that sends half a request line and stalls
    holds a connection slot until the per-connection read timeout
    reaps it (server closes; recv returns b''); the service answers
    the next well-formed request immediately."""
    import http.client
    import socket
    from tpuvsr.serve.http import ServiceHTTP
    spool = os.path.join(tmp, "spool")
    os.makedirs(spool, exist_ok=True)
    svc = ServiceHTTP(spool, request_timeout=0.5).start()
    try:
        s = socket.create_connection(("127.0.0.1", svc.port),
                                     timeout=10)
        s.sendall(b"POST /v1/jobs HT")      # ...and stall forever
        s.settimeout(10)
        try:
            reaped = s.recv(64) == b""      # server hung up on us
        except ConnectionError:
            reaped = True
        s.close()
        conn = http.client.HTTPConnection("127.0.0.1", svc.port,
                                          timeout=10)
        conn.request("GET", "/healthz")
        healthy = conn.getresponse().status == 200
        conn.close()
    finally:
        svc.stop()
    return {"ok": reaped and healthy, "reaped": reaped,
            "healthz_after": healthy}


def _spool_events(spool):
    from tpuvsr.obs import read_journal
    path = os.path.join(spool, "spool.jsonl")
    return read_journal(path) if os.path.exists(path) else []


#: the doomed POOL PARENT (host-death-failover): registers its host's
#: lease through the spool driver, then runs its worker — and SIGKILLs
#: the whole process at the depth-2 tick, after the level-1 checkpoint
#: has landed locally AND the same tick's replicate_snapshot() shipped
#: it into the driver blob store (fake host identity via TPUVSR_HOST)
_DOOMED_POOL = """\
import os, signal, sys
from tpuvsr.service.queue import JobQueue
from tpuvsr.service.worker import Worker

q = JobQueue(sys.argv[1])
q.host_heartbeat()                 # the pool parent's host lease

def on_level(worker, job, depth):
    if depth >= 2:
        os.kill(os.getpid(), signal.SIGKILL)

Worker(q, devices=2, owner="poolA-w0",
       on_level=on_level, light_threads=0).drain(max_jobs=1)
"""


def scenario_host_death_failover(tmp):
    """ISSUE 20: an ENTIRE HOST dies mid-sharded-job — the pool
    parent (which wrote host-lease heartbeats through the spool
    driver) and its worker are one SIGKILLed process, and the host's
    local checkpoint directory AND its spool replica die with it
    (the quorum keeps serving on the remaining majority).  The
    survivor host's
    ``recover_stale`` judges the dead host by its stale LEASE (claim
    heartbeats are irrelevant: heartbeat_timeout is an hour), sweeps
    its claim in one pass, restores the rescue from the DRIVER-HELD
    snapshot blob, and resumes the sharded job to a verdict
    bit-identical to an undisturbed oracle job's."""
    import subprocess
    import time
    from tpuvsr.obs import read_journal
    from tpuvsr.service.queue import JobQueue
    from tpuvsr.service.worker import Worker
    from tpuvsr.testing import subprocess_env
    flags = {"stub": True, "inv_x_bound": 2}
    spool = os.path.join(tmp, "spool")
    q = JobQueue(spool, driver="quorum", host_lease_timeout=1.0,
                 heartbeat_timeout=3600.0)
    doomed = q.submit("<stub:doomed>", engine="sharded", devices=2,
                      flags=dict(flags))
    env = subprocess_env({"TPUVSR_HOST": "hostA"})
    p = subprocess.run([sys.executable, "-c", _DOOMED_POOL, spool],
                       env=env, capture_output=True, text=True,
                       timeout=300)
    killed = p.returncode in (-9, 137)
    # hostA's disk dies with the host: the job's local checkpoint
    # directory is gone — only the driver-held blob can seed a rescue
    shutil.rmtree(q.checkpoint_path(doomed.job_id),
                  ignore_errors=True)
    # ... and so does hostA's spool replica: the quorum keeps serving
    # (and the replicated blob survives) on the remaining majority
    shutil.rmtree(os.path.join(spool, "replicas", "r0"),
                  ignore_errors=True)
    blob_held = q.drv.get_blob(f"ckpt-{doomed.job_id}.tar") is not None
    time.sleep(1.2)                    # hostA's lease goes stale
    os.environ["TPUVSR_HOST"] = "hostB"
    try:
        qb = JobQueue(spool, host_lease_timeout=1.0,
                      heartbeat_timeout=3600.0)
        qb.host_heartbeat()
        dead = sorted(qb.dead_hosts())
        recovered = qb.recover_stale()
        Worker(qb, devices=2, owner="poolB-w0",
               light_threads=0).drain()
    finally:
        os.environ.pop("TPUVSR_HOST", None)
    jd = qb.get(doomed.job_id)
    evs = read_journal(qb.journal_path(doomed.job_id))
    req = [e for e in evs if e["event"] == "job_requeued"]
    # the undisturbed oracle: the same sharded job on a fresh spool
    qo = JobQueue(os.path.join(tmp, "oracle"))
    oj = qo.submit("<stub:oracle>", engine="sharded", devices=2,
                   flags=dict(flags))
    Worker(qo, devices=2, light_threads=0).drain()
    oracle = qo.get(oj.job_id)
    live = (qb.spool_status()["replicas"] or {}).get("live")
    ok = (killed and blob_held and dead == ["hostA"]
          and live == 2
          and doomed.job_id in recovered
          and jd.state == "violated" and jd.attempts == 2
          and len(req) == 1 and req[0].get("dead_host") == "hostA"
          and (req[0].get("rescue") or {}).get("depth", 0) >= 1
          and oracle.state == "violated"
          and jd.result["violated"] == oracle.result["violated"]
          and jd.result["trace"] == oracle.result["trace"]
          and jd.result["distinct"] == oracle.result["distinct"])
    return {
        "ok": ok, "killed_rc": p.returncode, "blob_held": blob_held,
        "replicas_live": live,
        "dead_hosts": dead, "state": jd.state,
        "attempts": jd.attempts,
        "dead_host_in_requeue": req[0].get("dead_host") if req
        else None,
        "rescue_depth": (req[0].get("rescue") or {}).get("depth")
        if req else None,
        "trace_identical": (jd.result or {}).get("trace")
        == (oracle.result or {}).get("trace"),
    }


def scenario_spool_replica_loss(tmp):
    """ISSUE 20: one replica of the quorum spool is DELETED mid-drain.
    The service is unaffected (appends still reach write quorum, jobs
    keep completing with the exact fixpoint), the loss is journaled as
    ``replica_lost`` in the spool's own journal, and recreating the
    replica directory lets anti-entropy heal it back — journaled
    ``replica_rejoin``, replica log byte-identical to a surviving
    one's."""
    ORACLE = _oracle()
    from tpuvsr.service.queue import JobQueue
    from tpuvsr.service.worker import Worker
    spool = os.path.join(tmp, "spool")
    q = JobQueue(spool, driver="quorum")
    j1 = q.submit("<stub:1>", engine="device", flags={"stub": True})
    j2 = q.submit("<stub:2>", engine="device", flags={"stub": True})
    Worker(q, devices=1, light_threads=0).drain(max_jobs=1)
    r1 = os.path.join(spool, "replicas", "r1")
    shutil.rmtree(r1)                  # mid-drain: one replica dies
    # the ordinary drain loop keeps going — recover_stale inside it
    # runs the driver's housekeeping, which detects the loss
    Worker(q, devices=1, light_threads=0).drain()
    st_lost = q.spool_status()["replicas"]
    j3 = q.submit("<stub:3>", engine="device", flags={"stub": True})
    Worker(q, devices=1, light_threads=0).drain()
    jobs = [q.get(j.job_id) for j in (j1, j2, j3)]
    # rejoin: the operator recreates the directory; the next sweep's
    # anti-entropy copies the missing frames back, prefix-preserving
    os.makedirs(r1)
    q.recover_stale()
    st_back = q.spool_status()["replicas"]
    with open(os.path.join(spool, "replicas", "r0",
                           "jobs.jsonl"), "rb") as f:
        b0 = f.read()
    with open(os.path.join(r1, "jobs.jsonl"), "rb") as f:
        b1 = f.read()
    ev = [e["event"] for e in _spool_events(spool)]
    ok = (st_lost and st_lost["live"] == 2 and st_lost["total"] == 3
          and all(j.state == "done"
                  and j.result["distinct"] == ORACLE["distinct"]
                  and j.result["levels"] == ORACLE["levels"]
                  for j in jobs)
          and st_back and st_back["live"] == 3
          and b0 == b1 and len(b0) > 0
          and "replica_lost" in ev and "replica_rejoin" in ev)
    return {
        "ok": ok, "replicas_after_loss": st_lost,
        "replicas_after_rejoin": st_back,
        "jobs_done_through_loss": [j.state for j in jobs],
        "replica_log_byte_identical": b0 == b1,
        "spool_events": [e for e in ev
                         if e in ("replica_lost", "replica_rejoin")],
    }


def scenario_zombie_fence(tmp):
    """ISSUE 20: a worker that was recovered (its claim swept, the
    job re-run by a successor) REVIVES and tries to commit its stale
    outcome.  Claim-epoch fencing rejects the zombie's terminal
    append — FencedError, a ``fence`` event in the spool journal —
    so the successor's verdict stands untouched: exactly-once."""
    ORACLE = _oracle()
    import time
    from tpuvsr.service.queue import FencedError, JobQueue
    from tpuvsr.service.worker import Worker
    spool = os.path.join(tmp, "spool")
    q1 = JobQueue(spool, driver="objstore", heartbeat_timeout=0.2)
    job = q1.submit("<stub>", engine="device", flags={"stub": True})
    q1.transition(job.job_id, "admitted")
    # the zombie claims from a "remote" host (a same-host claim would
    # be judged by its live pid, not by heartbeat staleness)...
    os.environ["TPUVSR_HOST"] = "hostZ"
    try:
        claimed = q1.claim(job.job_id, owner="wZ") is not None
    finally:
        os.environ.pop("TPUVSR_HOST", None)
    time.sleep(0.3)                    # ...then stalls: no heartbeat
    q2 = JobQueue(spool, heartbeat_timeout=0.2)
    recovered = q2.recover_stale()
    # the zombie revives mid-successor-run — the exact danger window
    # (running -> failed is a LEGAL transition; only the epoch fence
    # can tell the stale holder from the live one)
    state = {"fenced": None}

    def on_level(worker, jb, depth):
        if state["fenced"] is None and depth >= 1:
            try:
                q1.finish(job.job_id, "failed",
                          reason="zombie-says-so")
                state["fenced"] = False
            except FencedError:
                state["fenced"] = True

    Worker(q2, devices=1, owner="wB", on_level=on_level,
           light_threads=0).drain()
    done = q2.get(job.job_id)
    fenced = state["fenced"] is True
    q2.refresh()
    final = q2.get(job.job_id)
    fences = [e for e in _spool_events(spool)
              if e["event"] == "fence"]
    ok = (claimed and job.job_id in recovered and fenced
          and done.state == "done" and done.attempts == 2
          and final.state == "done"
          and final.result["distinct"] == ORACLE["distinct"]
          and final.result["levels"] == ORACLE["levels"]
          and len(fences) >= 1
          and fences[0]["job_id"] == job.job_id)
    return {
        "ok": ok, "zombie_claimed": claimed,
        "recovered": recovered, "zombie_fenced": fenced,
        "final_state": final.state, "attempts": final.attempts,
        "fence_events": [(e["job_id"], e["epoch"]) for e in fences],
    }


SCENARIOS = [
    ("oom-degrade", scenario_oom_degrade),
    ("oom-paged-fallback", scenario_oom_paged_fallback),
    ("kill-rescue", scenario_kill_rescue),
    ("pack-kill-rescue", scenario_pack_kill_rescue),
    ("kill-fused-commit-resume", scenario_kill_fused_commit_resume),
    ("kill-canon-resume", scenario_kill_canon_resume),
    ("kill-spill-resume", scenario_kill_spill_resume),
    ("kill-bounds-resume", scenario_kill_bounds_resume),
    ("kill-por-resume", scenario_kill_por_resume),
    ("corrupt-ckpt", scenario_corrupt_ckpt),
    ("garble-ckpt", scenario_garble_ckpt),
    ("exchange-drop", scenario_exchange_drop),
    ("exchange-drop-retry", scenario_exchange_drop_retry),
    ("oom-mesh-degrade", scenario_oom_mesh_degrade),
    ("kill-elastic-resume", scenario_kill_elastic_resume),
    ("pipeline-faults", scenario_pipeline_faults),
    ("service-preempt-requeue", scenario_service_preempt_requeue),
    ("service-oom-degrade", scenario_service_oom_degrade),
    ("kill-one-of-n-workers", scenario_kill_one_of_n_workers),
    ("kill-aggregator-mid-tail", scenario_kill_aggregator_mid_tail),
    ("kill-worker-mid-event", scenario_kill_worker_mid_event),
    ("sim-oom-shrink", scenario_sim_oom_shrink),
    ("kill-hunt-resume", scenario_kill_hunt_resume),
    ("kill-validate-resume", scenario_kill_validate_resume),
    ("kill-liveness-resume", scenario_kill_liveness_resume),
    ("flood-rate-limit", scenario_flood_rate_limit),
    ("breaker-crash-loop", scenario_breaker_crash_loop),
    ("slow-loris-reap", scenario_slow_loris_reap),
    ("host-death-failover", scenario_host_death_failover),
    ("spool-replica-loss", scenario_spool_replica_loss),
    ("zombie-fence", scenario_zombie_fence),
]


def main(argv=None):
    only = (argv or [None])[0] if argv else None
    out = {}
    tmp = tempfile.mkdtemp(prefix="tpuvsr-fault-matrix-")
    try:
        for name, fn in SCENARIOS:
            if only and only not in name:
                continue
            sdir = os.path.join(tmp, name)
            os.makedirs(sdir, exist_ok=True)
            try:
                out[name] = fn(sdir)
            except Exception as e:  # noqa: BLE001 — report, don't die
                out[name] = {"ok": False,
                             "error": f"{type(e).__name__}: {e}"}
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    ok = all(v.get("ok") for v in out.values()) and bool(out)
    print(json.dumps({"ok": ok, "scenarios": out}, indent=1,
                     default=str))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
