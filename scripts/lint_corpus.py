"""Run the speclint static analyzer (tpuvsr/analysis) over the FULL
reference corpus — all eight registered models under their shipped (or,
for the 05/06 recovery-era specs that ship without one, synthesized)
cfgs — and report per-model findings.

This is the tier-1 lint gate: fast, CPU-only, no jit dispatch (the
drift pass instantiates codecs/kernels but never compiles a level
kernel).  Exit code 0 when every model is clean of error-severity
findings, 1 otherwise, 3 when the reference corpus is not mounted.

Usage:
    python scripts/lint_corpus.py [--json] [--bounds] [--independence]
                                  [only_stem_substr]

--json emits one JSON object: {model: report_dict, ...} plus an "ok"
summary key, mirroring the CLI's `-lint -json` per-spec shape.
--bounds adds a per-model bounds-pass column (ISSUE 13): tightened?,
dead-action count and the static state bound — the facts the engines
consume, read straight off each report's extras["bounds"] section.
--independence adds the pass-7 column (ISSUE 16): independent-pair
count, poisoned/invisible action tallies and monotone-witness count —
how much ample-set reduction each corpus model statically admits.
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tpuvsr.platform_select import force_cpu  # noqa: E402
force_cpu()

from tpuvsr.analysis import run_lint  # noqa: E402
from tpuvsr.engine.spec import SpecModel  # noqa: E402
from tpuvsr.frontend.cfg import parse_cfg_file, parse_cfg_text  # noqa: E402
from tpuvsr.frontend.parser import parse_module_file  # noqa: E402

REFERENCE = os.environ.get(
    "TPUVSR_REFERENCE", "/root/reference/vsr-revisited/paper")
ANALYSIS = f"{REFERENCE}/analysis"

# shipped-cfg models: stem -> (tla path, cfg path)
SHIPPED = {
    "vsr": (f"{REFERENCE}/VSR.tla", f"{REFERENCE}/VSR.cfg"),
    "a01": (f"{ANALYSIS}/01-view-changes/VR_ASSUME_NEWVIEWCHANGE.tla",
            f"{ANALYSIS}/01-view-changes/VR_ASSUME_NEWVIEWCHANGE.cfg"),
    "i01": (f"{ANALYSIS}/01-view-changes/VR_INC_RESEND.tla",
            f"{ANALYSIS}/01-view-changes/VR_INC_RESEND.cfg"),
    "st03": (f"{ANALYSIS}/03-state-transfer/VR_STATE_TRANSFER.tla",
             f"{ANALYSIS}/03-state-transfer/VR_STATE_TRANSFER.cfg"),
    "as04": (f"{ANALYSIS}/04-application-state/VR_APP_STATE.tla",
             f"{ANALYSIS}/04-application-state/VR_APP_STATE.cfg"),
}

# 05/06 ship without cfgs; synthesize minimal ones (same bindings as
# tests/test_corpus.py)
_COMMON = """
    Normal = Normal
    ViewChange = ViewChange
    StateTransfer = StateTransfer
    Recovering = Recovering
    PrepareMsg = PrepareMsg
    PrepareOkMsg = PrepareOkMsg
    StartViewChangeMsg = StartViewChangeMsg
    DoViewChangeMsg = DoViewChangeMsg
    StartViewMsg = StartViewMsg
    GetStateMsg = GetStateMsg
    NewStateMsg = NewStateMsg
    RecoveryMsg = RecoveryMsg
    RecoveryResponseMsg = RecoveryResponseMsg
    Nil = Nil
    AnyDest = AnyDest
"""

RECOVERY_CFG = """CONSTANTS
    ReplicaCount = 3
    Values = {v1}
    StartViewOnTimerLimit = 1
    NoProgressChangeLimit = 0
    CrashLimit = 1
""" + _COMMON + """
INIT Init
NEXT Next
VIEW view
INVARIANT
NoLogDivergence
NoAppStateDivergence
AcknowledgedWriteNotLost
CommitNumberNeverHigherThanOpNumber
"""

CP_CFG = """CONSTANTS
    ReplicaCount = 3
    Values = {v1}
    StartViewOnTimerLimit = 1
    NoProgressChangeLimit = 0
    CrashLimit = 1
""" + _COMMON + """
    GetCheckpointMsg = GetCheckpointMsg
    NewCheckpointMsg = NewCheckpointMsg
    NoOp = NoOp
INIT Init
NEXT Next
VIEW view
INVARIANT
NoLogDivergence
NoAppStateDivergence
AcknowledgedWriteNotLost
CommitNumberNeverHigherThanOpNumber
CommitNumberMatchesAppState
"""

SYNTHESIZED = {
    "rr05": (f"{ANALYSIS}/05-replica-recovery/VR_REPLICA_RECOVERY.tla",
             RECOVERY_CFG),
    "al05": (f"{ANALYSIS}/05-replica-recovery/"
             f"VR_REPLICA_RECOVERY_ASYNC_LOG.tla", RECOVERY_CFG),
    "cp06": (f"{ANALYSIS}/06-replica-recovery-cp/"
             f"VR_REPLICA_RECOVERY_CP.tla", CP_CFG),
}


def load_all(only=""):
    specs = {}
    for stem, (tla, cfg) in SHIPPED.items():
        if only in stem:
            specs[stem] = SpecModel(parse_module_file(tla),
                                    parse_cfg_file(cfg))
    for stem, (tla, cfg_text) in SYNTHESIZED.items():
        if only in stem:
            specs[stem] = SpecModel(parse_module_file(tla),
                                    parse_cfg_text(cfg_text))
    return specs


def _bounds_col(report):
    """One-line bounds summary column from a report's extras."""
    b = report.extras.get("bounds") or {}
    if not b:
        return "bounds: (pass did not run)"
    sb = b.get("state_bound")
    return (f"bounds: tightened={b.get('tightened')} "
            f"dead={len(b.get('dead_actions') or [])} "
            f"state_bound={'unbounded' if sb is None else sb}")


def _indep_col(report):
    """One-line independence summary column (ISSUE 16): the pairs the
    ample-set filter could consume plus the refusal tallies (poisoned
    actions, invariant-visible actions, monotone witnesses — the
    sharded proviso's currency)."""
    d = report.extras.get("independence") or {}
    if not d:
        return "independence: (pass did not run)"
    vis = d.get("visible") or {}
    mono = d.get("monotone") or {}
    return (f"independence: pairs={d.get('independent_pairs')} "
            f"actions={len(d.get('actions') or [])} "
            f"poisoned={len(d.get('poisoned') or {})} "
            f"invisible={sum(1 for v in vis.values() if not v)} "
            f"witnesses={sum(1 for v in mono.values() if v)} "
            f"digest={d.get('digest')}")


def main(argv):
    as_json = "--json" in argv
    with_bounds = "--bounds" in argv
    with_indep = "--independence" in argv
    rest = [a for a in argv if not a.startswith("--")]
    only = rest[0] if rest else ""

    if not os.path.isdir(REFERENCE):
        print(f"reference corpus not mounted at {REFERENCE} "
              f"(set TPUVSR_REFERENCE)", file=sys.stderr)
        return 3

    t0 = time.time()
    reports = {}
    for stem, spec in sorted(load_all(only).items()):
        ts = time.time()
        reports[stem] = (run_lint(spec), time.time() - ts)

    ok = all(r.ok for r, _ in reports.values())
    if as_json:
        out = {stem: dict(r.to_dict(), elapsed_s=round(dt, 3))
               for stem, (r, dt) in reports.items()}
        out["ok"] = ok
        print(json.dumps(out))
    else:
        for stem, (r, dt) in reports.items():
            print(f"==== {stem} ({dt:.2f}s)")
            if with_bounds:
                print(_bounds_col(r))
            if with_indep:
                print(_indep_col(r))
            print(r.render())
        print(f"==== corpus {'CLEAN' if ok else 'HAS ERRORS'} "
              f"({time.time() - t0:.2f}s total)")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
