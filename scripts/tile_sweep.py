"""Tile-size sweep of the device BFS engine on the flagship small
config — finds the throughput-optimal tile for the current backend.

The r4 first TPU bench (tile=256, the CPU-tuned default) measured the
tunneled v5e SLOWER than the 1-core CPU fallback (1,654 vs 6,564
distinct/s): at tile 256 each while_loop iteration does too little
parallel work to cover the TPU's per-iteration overheads.  This sweep
measures distinct/s at several tiles so bench.py can pick a per-backend
default honestly.

Usage: [TPUVSR_TPU=1] python scripts/tile_sweep.py [tile ...]
Writes scripts/tile_sweep.json.
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# the sweep IS the validation tool the engine's tile-width guard defers
# to — it must be able to run the unvalidated widths it grades
os.environ.setdefault("TPUVSR_UNSAFE_TILE", "1")

from tpuvsr.platform_select import ensure_backend, force_cpu

if os.environ.get("TPUVSR_TPU") != "1":
    force_cpu()
    backend = "cpu"
else:
    backend = ensure_backend(log=lambda m: print(f"[sweep] {m}",
                                                 file=sys.stderr,
                                                 flush=True))

from __graft_entry__ import _small_spec
from tpuvsr.engine.device_bfs import DeviceBFS

tiles = [int(a) for a in sys.argv[1:]] or [256, 512, 1024, 2048]
OUT = os.path.join(REPO, "scripts", "tile_sweep.json")

spec = _small_spec()
rows = []
for tile in tiles:
    eng = DeviceBFS(spec, tile_size=tile, fpset_capacity=1 << 21,
                    next_capacity=1 << 15, expand_mult=2,
                    expand_mults={"ReceiveMatchingSVC": 4, "SendDVC": 4})
    t0 = time.time()
    eng.run(max_depth=6)                      # compile + warm
    compile_s = time.time() - t0
    res = eng.run()                           # timed full fixpoint
    row = {
        "tile": tile,
        "backend": backend,
        "compile_s": round(compile_s, 1),
        "distinct": res.distinct_states,
        "generated": res.states_generated,
        "elapsed_s": round(res.elapsed, 2),
        "distinct_per_s": round(res.distinct_states / res.elapsed, 1),
        "generated_per_s": round(res.states_generated / res.elapsed, 1),
        "fixpoint": res.error is None,
        # the flagship config's pinned fixpoint — a row that misses it
        # is a CORRECTNESS failure at that tile width, not a datapoint
        # (first seen: tile 1024 on axon produced 58,957 distinct /
        # 147,728 generated — duplicate states entering the frontier)
        "correct": (res.distinct_states == 43941
                    and res.states_generated == 118746),
    }
    rows.append(row)
    print(json.dumps(row), flush=True)
    with open(OUT, "w") as f:
        json.dump({"rows": rows}, f, indent=1)
print("done", file=sys.stderr)
