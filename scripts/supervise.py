"""Restartable supervision wrapper around the tpuvsr CLI.

Runs ``python -m tpuvsr SPEC [flags...] -supervise`` and, while the
child exits with the resumable code (75 — a SIGTERM/SIGINT was turned
into a rescue checkpoint at a level boundary), re-runs it with
``-recover CKPT`` so a preempted multi-day run continues from the
snapshot with cumulative elapsed and one continuous journal.  In-run
OOM retry/degrade (tile halving -> paged fallback; with
``-engine sharded`` the mesh-aware ladder: tile halving -> mesh
shrink -> paged) happens INSIDE the child's supervisor; this wrapper
only restarts across process deaths.  A sharded restart that comes
back with fewer devices re-hash-partitions the snapshot onto the
smaller mesh at load time (elastic resume — the journal records a
``reshard`` event).

Signals sent to the wrapper are forwarded to the child — a SIGTERM to
the wrapper lets the child rescue-checkpoint, and the wrapper then
exits 75 itself instead of restarting (the outer scheduler decides).

Usage:
    python scripts/supervise.py SPEC.tla [tpuvsr flags ...]
                                [--max-restarts N]
"""

import os
import signal
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tpuvsr.exitcodes import EX_RESUMABLE as EXIT_RESUMABLE  # noqa: E402


def main(argv=None):
    args = list(sys.argv[1:] if argv is None else argv)
    max_restarts = 20
    if "--max-restarts" in args:
        i = args.index("--max-restarts")
        try:
            max_restarts = int(args[i + 1])
        except (IndexError, ValueError):
            print("supervise: --max-restarts needs an integer",
                  file=sys.stderr)
            return 2
        del args[i:i + 2]
    if not args or args[0].startswith("-"):
        print(__doc__, file=sys.stderr)
        return 2
    if "-supervise" not in args:
        args.append("-supervise")
    if "-checkpointdir" in args:
        ckpt = args[args.index("-checkpointdir") + 1]
    else:
        ckpt = os.path.splitext(args[0])[0] + ".ckpt"

    state = {"child": None, "forwarded": False}

    def forward(signum, frame):
        state["forwarded"] = True
        child = state["child"]
        if child is not None and child.poll() is None:
            child.send_signal(signum)

    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, forward)

    cmd = [sys.executable, "-m", "tpuvsr"] + args
    restarts = 0
    while True:
        state["child"] = subprocess.Popen(cmd, cwd=REPO)
        rc = state["child"].wait()
        if rc != EXIT_RESUMABLE or state["forwarded"]:
            return rc
        restarts += 1
        if restarts > max_restarts:
            print(f"supervise: giving up after {max_restarts} "
                  f"restarts (snapshot remains at {ckpt})",
                  file=sys.stderr)
            return rc
        print(f"supervise: restart {restarts}/{max_restarts}: "
              f"resuming from {ckpt}", file=sys.stderr)
        # always resume from the supervised run's OWN checkpoint dir —
        # a launch-time "-recover OLDDIR" must not pin every restart to
        # the stale original snapshot
        if "-recover" in cmd:
            cmd[cmd.index("-recover") + 1] = ckpt
        else:
            cmd = cmd + ["-recover", ckpt]


if __name__ == "__main__":
    sys.exit(main())
