"""Tunnel-watch loop (VERDICT r3 item 1).

Re-probes the axon TPU tunnel every few minutes for the whole round,
appending one JSON line per attempt to ``scripts/tpu_probe_log.jsonl``
so the tunnel's availability (or absence) is auditable.  When a probe
sees >0 devices it drops ``scripts/TPU_UP`` as a flag file and keeps
watching (the tunnel can flap).

Run detached:  python scripts/tpu_watch.py --interval 300
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpuvsr.platform_select import probe_tpu

LOG = os.path.join(os.path.dirname(os.path.abspath(__file__)), "tpu_probe_log.jsonl")
FLAG = os.path.join(os.path.dirname(os.path.abspath(__file__)), "TPU_UP")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=float, default=300.0)
    ap.add_argument("--timeout", type=float, default=75.0)
    ap.add_argument("--max-hours", type=float, default=13.0)
    args = ap.parse_args()

    t0 = time.time()
    while time.time() - t0 < args.max_hours * 3600:
        t = time.time()
        n = probe_tpu(args.timeout)
        rec = {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(t)),
            "probe_s": round(time.time() - t, 1),
            "devices": n,
        }
        with open(LOG, "a") as f:
            f.write(json.dumps(rec) + "\n")
        if n > 0:
            with open(FLAG, "w") as f:
                f.write(json.dumps(rec) + "\n")
        elif os.path.exists(FLAG):
            os.remove(FLAG)
        time.sleep(max(0.0, args.interval - (time.time() - t)))


if __name__ == "__main__":
    main()
