"""Tunnel-watch loop — DEPRECATED thin wrapper.

The probe loop moved into the dispatch service as its
backend-availability input: ``tpuvsr.service.scheduler.watch_backend``
(ISSUE 6 absorbed this script; the scheduler's cpu-vs-tpu placement
advisory reads the same availability signal).  This wrapper keeps the
historical entry point and artifact paths
(``scripts/tpu_probe_log.jsonl`` / ``scripts/TPU_UP``) alive:

Run detached:  python scripts/tpu_watch.py --interval 300
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tpuvsr.service.scheduler import watch_backend  # noqa: E402

HERE = os.path.dirname(os.path.abspath(__file__))
LOG = os.path.join(HERE, "tpu_probe_log.jsonl")
FLAG = os.path.join(HERE, "TPU_UP")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=float, default=300.0)
    ap.add_argument("--timeout", type=float, default=75.0)
    ap.add_argument("--max-hours", type=float, default=13.0)
    args = ap.parse_args()
    watch_backend(LOG, FLAG, interval=args.interval,
                  timeout=args.timeout, max_hours=args.max_hours)


if __name__ == "__main__":
    main()
