"""Manually re-execute the defect hunt's first chunk outside lax.scan,
tracking walker 3567: compare the device-path state against the
recorded-(aid,prm) replay at every step; print the first divergence."""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np
import jax
import jax.numpy as jnp

from tpuvsr.engine.spec import SpecModel
from tpuvsr.frontend.cfg import parse_cfg_file
from tpuvsr.frontend.parser import parse_module_file
from tpuvsr.engine.device_sim import DeviceSimulator
from tpuvsr.models.vsr_kernel import ACTION_NAMES

I32 = jnp.int32
W_TRACK = 3567
REFERENCE = "/root/reference/vsr-revisited/paper"
mod = parse_module_file(f"{REFERENCE}/VSR.tla")
cfg = parse_cfg_file(f"{REPO}/examples/VSR_defect.cfg")
spec = SpecModel(mod, cfg)

sim = DeviceSimulator(spec, walkers=4096, chunk_steps=32, max_msgs=48)
kern = sim.kern
codec = sim.codec
lane_aid = jnp.asarray(kern.lane_action)
lane_prm = jnp.asarray(kern.lane_param)
guards = kern._guard_fns()
fns = kern._action_fns()
inv = kern.invariant_fn(sim.inv_names)


def guard_all(st):
    outs = []
    for name, g in zip(ACTION_NAMES, guards):
        lanes = jnp.arange(kern._lane_count(name), dtype=I32)
        outs.append(jax.vmap(lambda ln, g=g: g(st, ln))(lanes))
    return jnp.concatenate(outs)


branches = [lambda st, p, f=f: f(st, p)[0] for f in fns]


def apply_lane(st, aid, prm):
    return jax.lax.switch(aid, branches, st, prm)


step_fn = jax.jit(lambda states, key: _step(states, key))


def _step(states, key):
    en = jax.vmap(guard_all)(states)
    u = jax.random.uniform(key, en.shape)
    lane = jnp.argmax(jnp.where(en, u, -1.0), axis=1)
    alive = en.any(axis=1)
    aid = lane_aid[lane]
    prm = lane_prm[lane]
    succ = jax.vmap(apply_lane)(states, aid, prm)
    sel = {k: alive.reshape((-1,) + (1,) * (v.ndim - 1))
           for k, v in states.items()}
    merged = {k: jnp.where(sel[k], succ[k], v) for k, v in states.items()}
    iok = jax.vmap(inv)(succ)
    return merged, alive, aid, prm, iok, succ


init_dense = [codec.encode(st) for st in spec.init_states()]
init = {k: jnp.asarray(np.repeat(np.stack([d[k] for d in init_dense])[:1],
                                 4096, axis=0)) for k in init_dense[0]}

key = jax.random.PRNGKey(0)
key, sub = jax.random.split(key)
keys = jax.random.split(sub, 32)

states = init
replay = {k: np.asarray(v[W_TRACK]) for k, v in init.items()}
mat_fns = {}


def mat_one(st, aid, prm):
    f = mat_fns.get(aid)
    if f is None:
        f = jax.jit(jax.vmap(fns[aid], in_axes=(0, 0)))
        mat_fns[aid] = f
    batch = {k: np.asarray(v)[None] for k, v in st.items()}
    succ, en = f(batch, jnp.asarray([prm], I32))
    return ({k: np.asarray(v)[0] for k, v in succ.items()
             if not k.startswith("_")}, bool(np.asarray(en)[0]))


for i in range(21):
    states, alive, aid, prm, iok, succ = step_fn(states, keys[i])
    a, p = int(aid[W_TRACK]), int(prm[W_TRACK])
    al, ok = bool(alive[W_TRACK]), bool(iok[W_TRACK])
    dev = {k: np.asarray(v[W_TRACK]) for k, v in states.items()}
    replay, ren = mat_one(replay, a, p)
    diffs = [k for k in dev if not np.array_equal(dev[k], replay[k])]
    print(f"step {i}: {ACTION_NAMES[a]}[{p}] alive={al} inv_ok={ok} "
          f"replay_en={ren} diffs={diffs}")
    if diffs:
        for k in diffs[:4]:
            print(f"  {k}:\n    dev:    {dev[k]}\n    replay: {replay[k]}")
        break
if not diffs:
    print("no divergence in 21 steps; device final inv:",
          bool(inv({k: jnp.asarray(v) for k, v in dev.items()})))
