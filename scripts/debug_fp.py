"""Debug the defect-hunt false positive: rerun the exact hunt
(deterministic PRNG), then check the replayed final state with both the
interpreter and the device invariant kernel, and re-walk the whole
trace through the interpreter validating each transition."""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np
import jax
import jax.numpy as jnp

from tpuvsr.engine.spec import SpecModel
from tpuvsr.frontend.cfg import parse_cfg_file
from tpuvsr.frontend.parser import parse_module_file
from tpuvsr.engine.device_sim import DeviceSimulator

REFERENCE = "/root/reference/vsr-revisited/paper"
mod = parse_module_file(f"{REFERENCE}/VSR.tla")
cfg = parse_cfg_file(f"{REPO}/examples/VSR_defect.cfg")
spec = SpecModel(mod, cfg)

sim = DeviceSimulator(spec, walkers=4096, chunk_steps=32, max_msgs=48)
res = sim.run(num=10**9, depth=64, seed=0, max_seconds=900,
              log=lambda m: print(f"hunt: {m}", file=sys.stderr))
print(f"ok={res.ok} violated={res.violated_invariant} steps={res.steps}")
if res.trace is None:
    sys.exit("no violation found")

final = res.trace[-1].state
print("interp check_invariants(final):", spec.check_invariants(final))
dense = sim.codec.encode(final)
inv = sim.kern.invariant_fn(sim.inv_names)
ok = inv({k: jnp.asarray(v) for k, v in dense.items()})
print("device inv ok on replayed final state:", bool(ok))

# validate every step of the trace through the interpreter
cur = res.trace[0].state
interp_ok = True
for te in res.trace[1:]:
    succs = dict()
    for aname, succ in spec.successors(cur):
        # match on full state equality
        pass
    # find a successor matching te.state under action te.action_name
    found = False
    for aname, succ in spec.successors(cur):
        if aname == te.action_name and succ == te.state:
            found = True
            break
    if not found:
        print(f"STEP {te.position} ({te.action_name}): interpreter has no "
              f"matching successor!")
        interp_ok = False
        break
    cur = te.state
print("interpreter trace validation:", "PASS" if interp_ok else "FAIL")
