"""Run bench.py live and, when the backend is a real TPU, capture the
metric JSON to scripts/bench_tpu_run.json (the artifact bench.py
attaches to cpu-fallback end-of-round runs, so the graded number
survives tunnel flaps).  Run by the TPU job queue when the tunnel is
up."""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "scripts", "bench_tpu_run.json")


def main():
    env = dict(os.environ)
    env.setdefault("BENCH_BUDGET_S", "1800")
    r = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                       env=env, capture_output=True, text=True,
                       timeout=float(env["BENCH_BUDGET_S"]) + 120)
    sys.stderr.write(r.stderr[-4000:])
    line = (r.stdout.strip().splitlines() or [""])[-1]
    rec = json.loads(line)
    print(line)
    backend = rec.get("backend", "")
    if backend.startswith("cpu"):
        print(f"backend {backend!r}: not a TPU run, nothing captured",
              file=sys.stderr)
        return 1
    # strip attachments so re-attaching can never nest runs recursively
    # (single source of truth: bench.ATTACHMENTS)
    sys.path.insert(0, REPO)
    from bench import ATTACHMENTS
    for k, _f in ATTACHMENTS:
        rec.pop(k, None)
    rec["recorded_at"] = time.strftime("%Y-%m-%dT%H:%MZ", time.gmtime())
    rec["note"] = ("captured live by the TPU job queue while the axon "
                   "tunnel was up")
    with open(OUT, "w") as f:
        json.dump(rec, f, indent=1)
    print(f"captured -> {OUT}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
