"""Repro: sharded-engine level divergence when exchange buckets
overflow/grow mid-run (found via the depth-14 multihost artifact:
518,843 'distinct' > the whole 43,941-state space, generated < distinct
— dedup collapse beyond the level where bucket overflows begin).
Forces tiny buckets on the flagship small config and compares exact
level sizes to the interpreter."""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
_f = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _f:
    os.environ["XLA_FLAGS"] = (
        _f + " --xla_force_host_platform_device_count=8").strip()

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))

import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
from jax.sharding import Mesh

from conftest import vsr_spec, interp_level_sizes
from tpuvsr.parallel.sharded_bfs import ShardedBFS

depth = int(sys.argv[1]) if len(sys.argv) > 1 else 10
bucket = int(sys.argv[2]) if len(sys.argv) > 2 else 128

spec = vsr_spec()
want = interp_level_sizes(spec, depth)
print("interp levels:", want, flush=True)
eng = ShardedBFS(spec, Mesh(np.array(jax.devices()[:8]), ("d",)),
                 tile=64, bucket_cap=bucket,
                 next_capacity=1 << 14, fpset_capacity=1 << 16)
res = eng.run(max_depth=depth,
              log=lambda m: print(" ", m, flush=True))
print("sharded levels:", eng.level_sizes, flush=True)
print("match:", eng.level_sizes == want,
      "distinct:", res.distinct_states,
      "gen:", res.states_generated, flush=True)
