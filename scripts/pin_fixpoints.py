"""Measure exact interpreter-BFS fixpoints for all six corpus specs at
pinned small constants — the standing differential oracle (SURVEY.md
§4.7).  TLC is not available in this image, so the interpreter engine
(collision-free dedup on exact canonical view values) is the oracle;
the device engines are differentially held to these counts.

Writes scripts/fixpoints.json: stem -> {constants, distinct, generated,
diameter, elapsed_s}.

Usage: python scripts/pin_fixpoints.py [max_states] [only_stem_substr]
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tpuvsr.platform_select import force_cpu
if os.environ.get("TPUVSR_TPU") != "1":
    force_cpu()

from tpuvsr.engine.bfs import bfs_check
from tpuvsr.engine.spec import SpecModel
from tpuvsr.frontend.cfg import parse_cfg_file, parse_cfg_text
from tpuvsr.frontend.parser import parse_module_file

REFERENCE = os.environ.get(
    "TPUVSR_REFERENCE", "/root/reference/vsr-revisited/paper")
ANALYSIS = f"{REFERENCE}/analysis"
OUT = os.environ.get("TPUVSR_FIXPOINT_OUT",
                     os.path.join(REPO, "scripts", "fixpoints.json"))

max_states = int(sys.argv[1]) if len(sys.argv) > 1 else 3_000_000
only = sys.argv[2] if len(sys.argv) > 2 else ""

_COMMON = """
    Normal = Normal
    ViewChange = ViewChange
    StateTransfer = StateTransfer
    Recovering = Recovering
    PrepareMsg = PrepareMsg
    PrepareOkMsg = PrepareOkMsg
    StartViewChangeMsg = StartViewChangeMsg
    DoViewChangeMsg = DoViewChangeMsg
    StartViewMsg = StartViewMsg
    GetStateMsg = GetStateMsg
    NewStateMsg = NewStateMsg
    RecoveryMsg = RecoveryMsg
    RecoveryResponseMsg = RecoveryResponseMsg
    Nil = Nil
    AnyDest = AnyDest
"""

SMALL = {
    "ReplicaCount": "3",
    "Values": "{v1}",
    "StartViewOnTimerLimit": "1",
}


def load(stem, cfg_text=None, overrides=None):
    mod = parse_module_file(f"{ANALYSIS}/{stem}.tla"
                            if "/" in stem else f"{REFERENCE}/{stem}.tla")
    if cfg_text is None:
        cfg = parse_cfg_file(f"{ANALYSIS}/{stem}.cfg"
                             if "/" in stem else f"{REFERENCE}/{stem}.cfg")
    else:
        cfg = parse_cfg_text(cfg_text)
    from tpuvsr.frontend.cfg import _parse_value
    for k, v in {**SMALL, **(overrides or {})}.items():
        if k in cfg.constants:
            cfg.constants[k] = _parse_value(v)
    cfg.symmetry = None
    return SpecModel(mod, cfg)


RECOVERY_CFG = ("CONSTANTS\n    ReplicaCount = 3\n    Values = {v1}\n"
                "    StartViewOnTimerLimit = 1\n"
                "    NoProgressChangeLimit = 0\n    CrashLimit = 1\n"
                + _COMMON +
                "\nINIT Init\nNEXT Next\nVIEW view\nINVARIANT\n"
                "NoLogDivergence\nNoAppStateDivergence\n"
                "AcknowledgedWriteNotLost\n"
                "CommitNumberNeverHigherThanOpNumber\n")

CP_CFG = ("CONSTANTS\n    ReplicaCount = 3\n    Values = {v1}\n"
          "    StartViewOnTimerLimit = 1\n"
          "    NoProgressChangeLimit = 0\n    CrashLimit = 1\n"
          + _COMMON +
          "    GetCheckpointMsg = GetCheckpointMsg\n"
          "    NewCheckpointMsg = NewCheckpointMsg\n    NoOp = NoOp\n"
          "INIT Init\nNEXT Next\nVIEW view\nINVARIANT\n"
          "NoLogDivergence\nNoAppStateDivergence\n"
          "AcknowledgedWriteNotLost\n"
          "CommitNumberNeverHigherThanOpNumber\n"
          "CommitNumberMatchesAppState\n")

JOBS = [
    ("VSR", None, {"RestartEmptyLimit": "0"}),
    ("01-view-changes/VR_ASSUME_NEWVIEWCHANGE", None, None),
    ("01-view-changes/VR_INC_RESEND", None, None),
    ("03-state-transfer/VR_STATE_TRANSFER", None, None),
    ("04-application-state/VR_APP_STATE", None, None),
    ("05-replica-recovery/VR_REPLICA_RECOVERY", RECOVERY_CFG, None),
    ("05-replica-recovery/VR_REPLICA_RECOVERY_ASYNC_LOG", RECOVERY_CFG,
     None),
    ("06-replica-recovery-cp/VR_REPLICA_RECOVERY_CP", CP_CFG, None),
]

def main():
    results = {}
    if os.path.exists(OUT):
        with open(OUT) as f:
            results = json.load(f)

    for stem, cfg_text, overrides in JOBS:
        if only and only not in stem:
            continue
        print(f"=== {stem}", flush=True)
        spec = load(stem, cfg_text, overrides)
        t0 = time.time()
        res = bfs_check(spec, max_states=max_states,
                        log=lambda m: print(f"  {m}", flush=True))
        el = time.time() - t0
        entry = {
            "constants": {k: repr(v) for k, v in sorted(
                spec.ev.constants.items())
                if k in ("ReplicaCount", "Values",
                         "StartViewOnTimerLimit", "RestartEmptyLimit",
                         "CrashLimit", "NoProgressChangeLimit",
                         "ClientCount")},
            "ok": res.ok,
            "fixpoint": res.error is None,
            "distinct": res.distinct_states,
            "generated": res.states_generated,
            "diameter": res.diameter,
            "elapsed_s": round(el, 1),
            "violated": res.violated_invariant,
            "error": res.error,
        }
        results[stem] = entry
        print(f"  -> {entry}", flush=True)
        with open(OUT, "w") as f:
            json.dump(results, f, indent=1, sort_keys=True)
    print("done")


if __name__ == "__main__":
    main()
