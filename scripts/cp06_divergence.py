"""Diagnose the CP06 device/interpreter invariant divergence hit by
recovery_fixpoints (device flags a violation after
ReceiveNewCheckpointMsg at parent gid ~1446; interpreter accepts)."""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "scripts"))
sys.argv = sys.argv[:1]

import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")

from pin_fixpoints import CP_CFG, load
from tpuvsr.core.values import TLAError
from tpuvsr.engine.device_bfs import DeviceBFS

spec = load("06-replica-recovery-cp/VR_REPLICA_RECOVERY_CP", CP_CFG,
            None)
eng = DeviceBFS(spec, tile_size=256)
err = None
try:
    eng.run(log=lambda m: print(" ", m, flush=True))
except TLAError as e:
    err = e
print("error:", err, flush=True)
if err is None:
    sys.exit("no divergence reproduced")

# the reported gid
import re
m = re.search(r"parent gid (\d+)", str(err))
gid = int(m.group(1))
aname = re.search(r"action (\w+)", str(err)).group(1)
print("parent gid", gid, "action", aname, flush=True)

parent = eng._trace(gid)[-1].state
codec, kern = eng.codec, eng.kern

# interpreter successors for this action + per-invariant verdicts
inv_names = list(spec.cfg.invariants)
print("invariants:", inv_names, flush=True)
interp_succs = [s for a, s in spec.successors(parent)
                if a.name == aname]
print(f"interp has {len(interp_succs)} {aname} successors; "
      f"interp verdicts:", flush=True)
for i, s in enumerate(interp_succs):
    print(f"  succ {i}: violated={spec.check_invariants(s)}",
          flush=True)

# kernel successors for this action with per-invariant device verdicts
dense = codec.encode(parent)
succs, enabled = kern.step_batch(
    {k: np.asarray(v)[None] for k, v in dense.items()})
enabled = np.asarray(enabled)[0]
succs = {k: np.asarray(v)[0] for k, v in succs.items()}
aid = kern.action_names.index(aname)
per_inv = {n: kern.invariant_fn([n]) for n in inv_names}
import jax.numpy as jnp
for lane in np.nonzero(enabled)[0]:
    if kern.lane_action[lane] != aid:
        continue
    d = {k: v[lane] for k, v in succs.items()}
    verdicts = {n: bool(np.asarray(fn(
        {k: jnp.asarray(v) for k, v in d.items()})))
        for n, fn in per_inv.items()}
    bad = [n for n, ok in verdicts.items() if not ok]
    st = codec.decode({k: v for k, v in d.items()
                       if not k.startswith("_")})
    ibad = spec.check_invariants(st)
    print(f"lane {lane}: device-bad={bad} interp-bad={ibad}",
          flush=True)
    if bad and not ibad:
        print("DIVERGENT lane; decoded successor state:", flush=True)
        for k in sorted(st):
            print("   ", k, "=", st[k], flush=True)
        break
