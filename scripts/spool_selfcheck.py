#!/usr/bin/env python
"""Spool self-check: validate a spool's own job history against a
spool-state spec through the trace validator (ISSUE 20).

The durable data plane's ``jobs`` stream IS a trace of the job state
machine — so the same machinery that validates counterexample traces
against a TLA+ spec (ISSUE 8) can validate the SERVICE's own journal
against a model of itself.  The check:

  1. reads the spool's ``jobs`` stream through the spool DRIVER
     (``fs`` / ``objstore`` / ``quorum`` — whatever the spool is
     configured as), so replicated spools self-check through the same
     quorum-merge read path the service uses;
  2. projects each job's history into one TRACE.jsonl record over the
     integer-coded state machine (``st`` = index into
     ``service.queue.STATES``) plus the claim epoch (``epoch`` =
     the ``attempts`` recorded on each ``running`` transition);
  3. validates the batch against the inline ``SpoolJob`` spec below —
     legal job-state transitions only, and claim EXCLUSIVITY per
     epoch: the only action that may touch ``epoch`` is ``Claim``,
     which bumps it by exactly one (a replayed/zombie epoch, an epoch
     skip, or any illegal state hop is a divergence localized at the
     exact journal record);
  4. proves the check has teeth by corrupting one projected record
     (an event's ``st`` rewritten to 0 — no action re-enters
     ``queued``) and requiring the validator to flag EXACTLY that
     step.

Given no spool, the drill builds one: a preempt-requeue job (two
claim epochs), a plain job and a cancelled job drained by the real
worker over ``--spool-driver`` (default quorum).

    python scripts/spool_selfcheck.py [SPOOL]
        [--spool-driver fs|objstore|quorum] [--trace-out FILE]

Prints one JSON object; exit 0 iff the spool's history validates AND
the corrupted leg diverges at the exact corrupted record.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

if __name__ == "__main__":
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    _fl = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _fl:
        os.environ["XLA_FLAGS"] = (
            _fl + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, REPO)

#: the job state machine as a spec — the mirror of
#: ``service.queue.LEGAL`` with states coded by their index in
#: ``service.queue.STATES`` (queued=0 admitted=1 running=2 done=3
#: violated=4 failed=5 preempted-requeued=6 cancelled=7).  ``Claim``
#: is the ONLY action that changes ``epoch``, and only by +1: claim
#: exclusivity per epoch, checkable from the journal alone.
SPOOL_SPEC = r"""---- MODULE SpoolJob ----
EXTENDS Naturals
CONSTANTS MaxEpoch
VARIABLES st, epoch

Init == st = 0 /\ epoch = 0

Admit ==
    /\ st = 0
    /\ st' = 1
    /\ UNCHANGED epoch

Claim ==
    /\ (st = 1 \/ st = 6)
    /\ epoch < MaxEpoch
    /\ st' = 2
    /\ epoch' = epoch + 1

Done ==
    /\ st = 2
    /\ st' = 3
    /\ UNCHANGED epoch

Violate ==
    /\ st = 2
    /\ st' = 4
    /\ UNCHANGED epoch

Fail ==
    /\ (st = 0 \/ st = 2)
    /\ st' = 5
    /\ UNCHANGED epoch

Requeue ==
    /\ st = 2
    /\ st' = 6
    /\ UNCHANGED epoch

Cancel ==
    /\ (st = 0 \/ st = 1 \/ st = 2 \/ st = 6)
    /\ st' = 7
    /\ UNCHANGED epoch

Next == Admit \/ Claim \/ Done \/ Violate \/ Fail \/ Requeue \/ Cancel

Legal == st <= 7 /\ epoch <= MaxEpoch
====
"""

SPOOL_CFG = ("CONSTANTS\n    MaxEpoch = %d\n"
             "INIT Init\nNEXT Next\nINVARIANT Legal\n")

#: journal state name -> spec action name
ACTION = {"admitted": "Admit", "running": "Claim", "done": "Done",
          "violated": "Violate", "failed": "Fail",
          "preempted-requeued": "Requeue", "cancelled": "Cancel"}


def spool_spec(max_epoch=6):
    from tpuvsr.engine.spec import SpecModel
    from tpuvsr.frontend.cfg import parse_cfg_text
    from tpuvsr.frontend.parser import parse_module_text
    return SpecModel(parse_module_text(SPOOL_SPEC),
                     parse_cfg_text(SPOOL_CFG % int(max_epoch)))


def project(spool):
    """TRACE.jsonl records (one per job) from the spool's ``jobs``
    stream, read through the spool's configured driver."""
    from tpuvsr.service.queue import STATES, JobQueue
    code = {s: i for i, s in enumerate(STATES)}
    q = JobQueue(spool)
    recs, _ = q.drv.read("jobs", None)
    jobs, order = {}, []
    for rec in recs:
        jid = rec.get("job_id")
        if rec.get("op") == "submit":
            jobs.setdefault(jid, {"events": [], "epoch": 0})
            order.append(jid)
        elif rec.get("op") == "state" and rec.get("state") in ACTION:
            j = jobs.setdefault(jid, {"events": [], "epoch": 0})
            if jid not in order:
                order.append(jid)
            st = rec["state"]
            if st == "running":
                j["epoch"] = int(rec.get("attempts", j["epoch"] + 1))
            j["events"].append({
                "action": ACTION[st],
                "vars": {"st": str(code[st]),
                         "epoch": str(j["epoch"])}})
    return [{"trace": jid, "init": {"st": "0", "epoch": "0"},
             "events": jobs[jid]["events"]}
            for jid in order if jobs[jid]["events"]]


def _demo_spool(tmp, driver):
    """A small real spool: a preempt-requeued job (two claim epochs),
    a plain job and a cancel — all through the actual worker."""
    from tpuvsr.service.queue import JobQueue
    from tpuvsr.service.worker import Worker
    spool = os.path.join(tmp, "spool")
    q = JobQueue(spool, driver=driver)
    q.submit("<stub:requeued>", engine="device",
             flags={"stub": True, "inject": "kill@level=3"})
    q.submit("<stub:plain>", engine="device", flags={"stub": True})
    victim = q.submit("<stub:cancelled>", engine="device",
                      flags={"stub": True})
    q.cancel(victim.job_id)
    Worker(q, devices=1, light_threads=0).drain()
    return spool


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("spool", nargs="?", default=None,
                    help="spool to self-check (default: build a "
                         "demo spool and check that)")
    ap.add_argument("--spool-driver", default="quorum",
                    choices=("fs", "objstore", "quorum"),
                    help="driver for the built demo spool "
                         "(an existing SPOOL auto-detects)")
    ap.add_argument("--trace-out", default=None, metavar="FILE",
                    help="also write the projected TRACE.jsonl here")
    args = ap.parse_args(argv)

    import shutil
    from tpuvsr.validate import host_validate_batch
    from tpuvsr.validate.traces import save_traces, traces_from_records

    tmp = None
    spool = args.spool
    if spool is None:
        tmp = tempfile.mkdtemp(prefix="tpuvsr-spool-selfcheck-")
        spool = _demo_spool(tmp, args.spool_driver)
    try:
        records = project(spool)
        max_epoch = max((int(e["vars"]["epoch"])
                         for r in records for e in r["events"]),
                        default=0) + 2
        spec = spool_spec(max_epoch)
        if args.trace_out:
            save_traces(args.trace_out, records)
        res = host_validate_batch(spec,
                                  traces_from_records(records, spec))

        # the teeth: corrupt ONE record — the longest job history,
        # final event's st rewritten to 0 ("queued"; no action
        # re-enters it) — and demand divergence EXACTLY there
        victim = max(records, key=lambda r: len(r["events"]))
        bad = json.loads(json.dumps(victim))
        k = len(bad["events"]) - 1
        bad["events"][k]["vars"]["st"] = "0"
        bres = host_validate_batch(spec,
                                   traces_from_records([bad], spec))
        fd = bres.first_divergence or {}
        out = {
            "spool": spool,
            "driver": json.load(open(os.path.join(
                spool, "spooldrv.json")))["driver"]
            if os.path.exists(os.path.join(spool, "spooldrv.json"))
            else "fs",
            "jobs": len(records),
            "events": sum(len(r["events"]) for r in records),
            "accepted": bool(res.ok),
            "corrupted_job": victim["trace"],
            "corrupted_step": k,
            "corrupted_diverged_at": fd.get("step"),
            "corrupted_flagged": (not bres.ok
                                  and fd.get("step") == k
                                  and fd.get("trace")
                                  == victim["trace"]),
        }
        out["ok"] = bool(out["accepted"] and out["corrupted_flagged"]
                         and out["jobs"] > 0)
    finally:
        if tmp:
            shutil.rmtree(tmp, ignore_errors=True)
    print(json.dumps(out, indent=1, default=str))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
