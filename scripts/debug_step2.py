"""Diff the kernel's TimerSendSVC successor against the interpreter's,
from the defect-config initial state."""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np
import jax.numpy as jnp

from tpuvsr.engine.spec import SpecModel
from tpuvsr.frontend.cfg import parse_cfg_file
from tpuvsr.frontend.parser import parse_module_file
from tpuvsr.models.vsr import VSRCodec
from tpuvsr.models.vsr_kernel import ACTION_NAMES, VSRKernel

REFERENCE = "/root/reference/vsr-revisited/paper"
mod = parse_module_file(f"{REFERENCE}/VSR.tla")
cfg = parse_cfg_file(f"{REPO}/examples/VSR_defect.cfg")
spec = SpecModel(mod, cfg)

codec = VSRCodec(spec.ev.constants, max_msgs=48)
kern = VSRKernel(codec)

init = list(spec.init_states())[0]
dense = codec.encode(init)
dec = codec.decode(dense)

# sanity: encode/decode roundtrip vs raw init
for k in init:
    if init[k] != dec[k]:
        print(f"ROUNDTRIP MISMATCH on {k}:\n  raw: {init[k]}\n  dec: {dec[k]}")

aid = ACTION_NAMES.index("TimerSendSVC")
fn = kern._action_fns()[aid]
for prm in range(3):
    st = {k: jnp.asarray(v) for k, v in dense.items()}
    succ, en = fn(st, jnp.asarray(prm, jnp.int32))
    succ = {k: np.asarray(v) for k, v in succ.items()
            if not k.startswith("_")}
    print(f"lane {prm}: enabled={bool(en)}")
    if not bool(en):
        continue
    ksucc = codec.decode(succ)
    matches = []
    for a, isucc in spec.successors(dec):
        if a.name != "TimerSendSVC":
            continue
        same = all(isucc[k] == ksucc[k] for k in isucc)
        matches.append(same)
        if same:
            break
    if not any(matches):
        print(f"  NO MATCH among {len(matches)} interp TimerSendSVC succs")
        # print field diffs vs first interp successor
        a, isucc = [x for x in spec.successors(dec)
                    if x[0].name == "TimerSendSVC"][prm]
        for k in isucc:
            if isucc[k] != ksucc[k]:
                print(f"  field {k}:\n    interp: {isucc[k]}\n"
                      f"    kernel: {ksucc[k]}")
    else:
        print("  match ok")
