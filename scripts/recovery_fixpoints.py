"""Pin exact full fixpoints for the recovery-era specs (RR05/AL05/CP06)
with the DEVICE engine — the interpreter oracle could not reach them
(scripts/fixpoints.json: RR05/AL05 hit the 300k-state limit at ~75
states/s; CP06 did finish at 137,524, which doubles as the cross-check
that the device fixpoint machinery agrees with the interpreter on a
recovery-era spec before we trust its RR05/AL05 numbers).

CP06 is run through BOTH the single-device engine and the sharded
engine (8-way virtual CPU mesh) — two independently-written dedup/
frontier paths; agreement on (distinct, generated, diameter) plus the
interpreter's 137,524 is the evidence standard.  RR05/AL05 proved far
larger than the interpreter bound suggested (RR05 passed 2M distinct
at depth 44), so they are pinned as BOUNDED oracles: single-device
engine to a state cap, exact level-size prefix recorded.  Device dedup
is on 128-bit fingerprints (collision odds at 1e6 states ~ 1e-26), vs
the interpreter's exact canonical views.

Writes scripts/recovery_fixpoints.json.

Usage: python scripts/recovery_fixpoints.py [only_stem_substr]
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
from tpuvsr.platform_select import force_cpu
force_cpu()

OUT = os.path.join(REPO, "scripts", "recovery_fixpoints.json")
only = sys.argv[1] if len(sys.argv) > 1 else ""
# pin_fixpoints parses sys.argv at import time (its own max_states arg)
sys.argv = sys.argv[:1]

sys.path.insert(0, os.path.join(REPO, "scripts"))
from pin_fixpoints import RECOVERY_CFG, CP_CFG, load  # noqa: E402

from tpuvsr.engine.device_bfs import DeviceBFS  # noqa: E402

# CP06 first: its interpreter fixpoint (137,524) is the cross-check
# that the device fixpoint machinery agrees with the oracle on a
# recovery-era spec, and it is small enough for BOTH engines.  The
# RR05/AL05 spaces turned out to be far larger (RR05 passed 2M distinct
# at depth 44 on the first attempt), so they get the single-device
# engine only, with a state cap as the bounded pinning fallback.
CAP = 6_000_000
JOBS = [
    ("06-replica-recovery-cp/VR_REPLICA_RECOVERY_CP", CP_CFG,
     ("single", "sharded"), None),
    ("05-replica-recovery/VR_REPLICA_RECOVERY", RECOVERY_CFG,
     ("single",), CAP),
    ("05-replica-recovery/VR_REPLICA_RECOVERY_ASYNC_LOG", RECOVERY_CFG,
     ("single",), CAP),
]

results = {}
if os.path.exists(OUT):
    with open(OUT) as f:
        results = json.load(f)


def _obs(key, engine):
    """Per-job observer (ISSUE 3 satellite / ROADMAP follow-up): round
    artifacts carry the journal + metrics trajectory of every pinning
    run, not just its headline counts."""
    from tpuvsr.obs import RunObserver
    stem = os.path.join(REPO, "scripts",
                        f"recovery_{key.lower()}_{engine}")
    return RunObserver(journal_path=stem + ".jsonl",
                       metrics_path=stem + "_metrics.json")


def run_single(spec, max_states=None, key=""):
    eng = DeviceBFS(spec, tile_size=512)
    res = eng.run(max_states=max_states, obs=_obs(key, "single"),
                  log=lambda m: print(f"  [single] {m}", flush=True))
    return res, eng.level_sizes


def run_sharded(spec, max_states=None, key=""):
    import numpy as np
    import jax
    from jax.sharding import Mesh
    from tpuvsr.parallel.sharded_bfs import ShardedBFS
    mesh = Mesh(np.array(jax.devices()[:8]), ("d",))
    eng = ShardedBFS(spec, mesh, tile=64, bucket_cap=4096,
                     next_capacity=1 << 15, fpset_capacity=1 << 17)
    res = eng.run(max_states=max_states, obs=_obs(key, "sharded"),
                  log=lambda m: print(f"  [sharded] {m}", flush=True))
    return res, eng.level_sizes


RUNNERS = {"single": run_single, "sharded": run_sharded}

for stem, cfg_text, engines, cap in JOBS:
    if only and only not in stem:
        continue
    key = stem.split("/")[-1]
    print(f"=== {stem}", flush=True)
    entry = results.get(key, {})
    for engine in engines:
        done = entry.get(engine, {})
        if done.get("fixpoint") or (cap and done.get("distinct")):
            print(f"  {engine}: already pinned, skipping", flush=True)
            continue
        spec = load(stem, cfg_text, None)
        t0 = time.time()
        try:
            res, levels = RUNNERS[engine](spec, max_states=cap, key=key)
        except Exception as e:  # noqa: BLE001
            entry[engine] = {"error": f"{type(e).__name__}: {e}"}
            results[key] = entry
            with open(OUT, "w") as f:
                json.dump(results, f, indent=1, sort_keys=True)
            print(f"  {engine} FAILED: {e}", flush=True)
            continue
        entry[engine] = {
            "ok": res.ok,
            "fixpoint": res.error is None,
            "distinct": res.distinct_states,
            "generated": res.states_generated,
            "diameter": res.diameter,
            "elapsed_s": round(time.time() - t0, 1),
            "violated": res.violated_invariant,
            "error": res.error,
            "level_sizes": levels,
            "journal": f"scripts/recovery_{key.lower()}_{engine}.jsonl",
            "metrics_file": (f"scripts/recovery_{key.lower()}_{engine}"
                             f"_metrics.json"),
            "phases": (res.metrics or {}).get("phases"),
        }
        results[key] = entry
        with open(OUT, "w") as f:
            json.dump(results, f, indent=1, sort_keys=True)
        print(f"  {engine} -> distinct={res.distinct_states} "
              f"generated={res.states_generated} diam={res.diameter} "
              f"({entry[engine]['elapsed_s']}s)", flush=True)
    s, sh = entry.get("single", {}), entry.get("sharded", {})
    if s.get("fixpoint") and sh.get("fixpoint"):
        agree = all(s.get(k) == sh.get(k) for k in
                    ("distinct", "generated", "diameter", "level_sizes"))
        entry["engines_agree"] = agree
        if key == "VR_REPLICA_RECOVERY_CP":
            entry["matches_interpreter_137524"] = (
                s.get("distinct") == 137524)
        results[key] = entry
        with open(OUT, "w") as f:
            json.dump(results, f, indent=1, sort_keys=True)
        print(f"  engines_agree={agree}", flush=True)

print("done")
