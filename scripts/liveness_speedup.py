"""Streamed-vs-two-pass-vs-interp behavior-graph A/B (ISSUE 15).

Measures the three graph-construction paths on the A01 liveness
ladder pins — the streamed single pass (edges flowing out of the
fused commit, ``DeviceGraph(mode="stream")``), the historical
two-pass retained-levels + re-expansion body (``mode="two-pass"``)
and the interpreter reference — and checks the bit-identity contract
between them (identical CSR modulo edge order within a source's
segment, identical verdicts).

Ladder (the v2t1 ladder, largest pin = BENCH_r05's `i01-v2t1`
bottleneck config): |Values|=1/timer=0 -> |Values|=1/timer=1 ->
|Values|=2/timer=1.  Pass ``--pin N`` to run only ladder rung N,
``--skip-interp`` to drop the interpreter leg (it is the slow one),
``--skip-two-pass`` to drop the re-expansion leg, ``--stub`` to run
the reference-free stub-harness proxy (the tier-1 acceptance proxy
for ``graph_overhead_ratio``).

Headline keys (bench.py lifts them into the round doc;
scripts/compare_bench.py's ``gate_liveness`` gates on them):
``mode``, ``edges``, ``edges_per_s``, ``graph_overhead_ratio``,
``check_s``.

Writes scripts/liveness_speedup.json.

Usage: python scripts/liveness_speedup.py [--pin N] [--skip-interp]
       [--skip-two-pass] [--stub]
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

STUB = "--stub" in sys.argv
if STUB:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

from tpuvsr.platform_select import ensure_backend  # noqa: E402

backend = ensure_backend(log=lambda m: print(f"[liveness] {m}",
                                             flush=True))

from tpuvsr.engine.device_liveness import DeviceGraph  # noqa: E402
from tpuvsr.engine.liveness import build_graph, liveness_check  # noqa: E402

REFERENCE = os.environ.get(
    "TPUVSR_REFERENCE", "/root/reference/vsr-revisited/paper")
PATH = f"{REFERENCE}/analysis/01-view-changes/VR_ASSUME_NEWVIEWCHANGE"

#: the v2t1 ladder: (|Values|, StartViewOnTimerLimit)
LADDER = [(1, 0), (1, 1), (2, 1)]

skip_interp = "--skip-interp" in sys.argv
skip_two_pass = "--skip-two-pass" in sys.argv
pin_only = None
if "--pin" in sys.argv:
    pin_only = int(sys.argv[sys.argv.index("--pin") + 1])


def _log(m):
    print(f"[liveness] {m}", flush=True)


def _ref_spec(values, timer, spec_formula=None):
    from tpuvsr.core.values import ModelValue
    from tpuvsr.engine.spec import SpecModel
    from tpuvsr.frontend.cfg import parse_cfg_file
    from tpuvsr.frontend.parser import parse_module_file
    mod = parse_module_file(f"{PATH}.tla")
    cfg = parse_cfg_file(f"{PATH}.cfg")
    cfg.constants["Values"] = frozenset(
        ModelValue(f"v{i + 1}") for i in range(values))
    cfg.constants["StartViewOnTimerLimit"] = timer
    if spec_formula:
        cfg.specification = spec_formula
    return SpecModel(mod, cfg)


def _graph_leg(make_graph, spec, label):
    t0 = time.time()
    g = make_graph()
    graph_s = round(time.time() - t0, 2)
    t0 = time.time()
    res = liveness_check(spec, graph=g)
    check_s = round(time.time() - t0, 2)
    leg = {"graph_s": graph_s, "check_s": check_s,
           "bfs_s": round(g.bfs_elapsed, 2),
           "graph_overhead_ratio": g.graph_overhead_ratio,
           "edges": int(g.csr[1].shape[0]),
           "edges_per_s": g.edges_per_s,
           "states": g.n,
           "verdict": {"ok": res.ok, "property": res.property_name}}
    _log(f"{label}: {g.n} states, {leg['edges']} edges, graph "
         f"{graph_s}s (overhead {g.graph_overhead_ratio}), check "
         f"{check_s}s -> ok={res.ok}")
    return g, leg


def run_pin(values, timer, spec_builder, graph_kw):
    pin = {"config": f"|Values|={values}, timer={timer}"}
    spec = spec_builder()
    gs, pin["streamed"] = _graph_leg(
        lambda: DeviceGraph(spec, mode="stream", **graph_kw),
        spec, "streamed")
    if not skip_two_pass:
        from tpuvsr.testing import canon_csr
        gt, pin["two_pass"] = _graph_leg(
            lambda: DeviceGraph(spec, mode="two-pass", **graph_kw),
            spec, "two-pass")
        pin["csr_identical"] = canon_csr(gs) == canon_csr(gt)
        pin["verdicts_match"] = (pin["streamed"]["verdict"]
                                 == pin["two_pass"]["verdict"])
    if not skip_interp:
        t0 = time.time()
        graph = build_graph(spec)
        pin["interp_graph_s"] = round(time.time() - t0, 1)
        ires = liveness_check(spec, graph=graph)
        pin["interp_verdict"] = {"ok": ires.ok,
                                 "property": ires.property_name}
        pin["interp_edges"] = sum(len(e) for e in graph[1])
        pin["interp_match"] = (
            pin["interp_verdict"] == pin["streamed"]["verdict"]
            and pin["interp_edges"] == pin["streamed"]["edges"])
        pin["graph_speedup_vs_interp"] = round(
            pin["interp_graph_s"]
            / max(pin["streamed"]["graph_s"], 1e-9), 1)
    return pin


# mode uses the DeviceGraph vocabulary ("stream" / "two-pass") so
# gate_liveness compares like with like across doc forms
out = {"backend": backend, "mode": "stream", "pins": []}

if STUB:
    # reference-free proxy: the Ticker liveness fixture through the
    # REAL engines (the tier-1 graph_overhead_ratio acceptance proxy)
    from tpuvsr.testing import stub_ticker_factory, ticker_spec
    out["config"] = "stub Ticker proxy (no reference mount)"
    pin = run_pin(
        0, 0, lambda: ticker_spec(modulus=12),
        dict(tile_size=4, chunk_tiles=2, hash_mode="full",
             fpset_capacity=1 << 8, next_capacity=1 << 6,
             model_factory=stub_ticker_factory(modulus=12)))
    pin["config"] = "stub Ticker, modulus=12"
    out["pins"].append(pin)
else:
    for i, (values, timer) in enumerate(LADDER):
        if pin_only is not None and i != pin_only:
            continue
        out["pins"].append(run_pin(
            values, timer,
            lambda v=values, t=timer: _ref_spec(v, t),
            dict(tile_size=128)))

# headline = the largest pin that ran (bench.py lifts these)
if out["pins"]:
    head = out["pins"][-1]
    out["edges"] = head["streamed"]["edges"]
    out["edges_per_s"] = head["streamed"]["edges_per_s"]
    out["graph_overhead_ratio"] = \
        head["streamed"]["graph_overhead_ratio"]
    out["check_s"] = head["streamed"]["check_s"]
    out["csr_identical"] = head.get("csr_identical")

with open(os.path.join(REPO, "scripts", "liveness_speedup.json"),
          "w") as f:
    json.dump(out, f, indent=1)
print(json.dumps(out))
