"""Measure device-vs-interpreter behavior-graph construction on the
A01 liveness oracle config (VERDICT r3 item 3 done-criterion: verdicts
through the device-built graph match the interpreter path, with a
measured graph-construction speedup).

Config: VR_ASSUME_NEWVIEWCHANGE at R=3, Values={v1}, timer=1 — the
pinned 42,753-state fixpoint (BASELINE.md), the largest size the
interpreter graph builder is known to finish (813 s for the BFS alone,
scripts/fixpoints.json).

Writes scripts/liveness_speedup.json.

Usage: python scripts/liveness_speedup.py [--skip-interp]
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tpuvsr.platform_select import ensure_backend

backend = ensure_backend(log=lambda m: print(f"[liveness] {m}",
                                             flush=True))

from tpuvsr.core.values import ModelValue                 # noqa: E402
from tpuvsr.engine.device_liveness import DeviceGraph     # noqa: E402
from tpuvsr.engine.liveness import build_graph, liveness_check  # noqa: E402
from tpuvsr.engine.spec import SpecModel                  # noqa: E402
from tpuvsr.frontend.cfg import parse_cfg_file            # noqa: E402
from tpuvsr.frontend.parser import parse_module_file      # noqa: E402

REFERENCE = os.environ.get(
    "TPUVSR_REFERENCE", "/root/reference/vsr-revisited/paper")
PATH = f"{REFERENCE}/analysis/01-view-changes/VR_ASSUME_NEWVIEWCHANGE"

skip_interp = "--skip-interp" in sys.argv


def _spec(spec_formula=None):
    mod = parse_module_file(f"{PATH}.tla")
    cfg = parse_cfg_file(f"{PATH}.cfg")
    cfg.constants["Values"] = frozenset({ModelValue("v1")})
    cfg.constants["StartViewOnTimerLimit"] = 1
    if spec_formula:
        cfg.specification = spec_formula
    return SpecModel(mod, cfg)


out = {"config": "A01 @ R=3, |Values|=1, timer=1 (42,753 states)",
       "backend": backend}

spec = _spec()
t0 = time.time()
g = DeviceGraph(spec, tile_size=128,
                log=lambda m: print(f"[liveness] {m}", flush=True))
out["device_graph_s"] = round(time.time() - t0, 1)
out["states"] = g.n
out["edges"] = sum(len(e) for e in g.edges)

t0 = time.time()
res = liveness_check(spec, graph=g)
out["device_verdict_livenessspec"] = {
    "ok": res.ok, "property": res.property_name,
    "check_s": round(time.time() - t0, 1)}

spec2 = _spec("Spec")            # fairness-free: ConvergenceToView breaks
t0 = time.time()
res2 = liveness_check(spec2, graph=g)
out["device_verdict_spec_nofairness"] = {
    "ok": res2.ok, "property": res2.property_name,
    "check_s": round(time.time() - t0, 1)}

if not skip_interp:
    t0 = time.time()
    graph = build_graph(_spec())
    out["interp_graph_s"] = round(time.time() - t0, 1)
    ires = liveness_check(_spec(), graph=graph)
    ires2 = liveness_check(_spec("Spec"), graph=graph)
    out["interp_verdict_livenessspec"] = {"ok": ires.ok,
                                          "property": ires.property_name}
    out["interp_verdict_spec_nofairness"] = {
        "ok": ires2.ok, "property": ires2.property_name}
    out["graph_speedup"] = round(out["interp_graph_s"]
                                 / out["device_graph_s"], 1)
    out["verdicts_match"] = (ires.ok == res.ok
                             and ires2.ok == res2.ok
                             and ires2.property_name == res2.property_name)

with open(os.path.join(REPO, "scripts", "liveness_speedup.json"),
          "w") as f:
    json.dump(out, f, indent=1)
print(json.dumps(out))
