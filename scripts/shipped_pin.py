"""Pin the SHIPPED VSR.cfg safety fixpoint (VERDICT r4 item 5).

Every exact pin so far used shrunken constants; the reference's shipped
flagship config — R=3, C=1, |Values|=2, StartViewOnTimerLimit=2,
RestartEmptyLimit=0, SYMMETRY symmValues ON, INVARIANT
AcknowledgedWriteNotLost (VSR.cfg:4-8,29-37, loaded UNCHANGED) — has
never been run to fixpoint.  This script runs it through the paged
engine in resumable wall-clock windows (checkpoint scripts/shipped_ckpt)
and records the fixpoint when reached, or an honest bounded pin.

This is also the first at-scale run with symmetry canonicalization ON
(|Values|=2 -> min over 2 permutations per fingerprint).

Writes scripts/shipped_pin.json.

Usage: [TPUVSR_TPU=1] python scripts/shipped_pin.py [seconds] [tile]
           [chunk_tiles]
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tpuvsr.platform_select import ensure_backend, force_cpu

if os.environ.get("TPUVSR_TPU") == "1":
    backend = ensure_backend(log=lambda m: print(f"[shipped] {m}",
                                                 flush=True))
else:
    force_cpu()
    backend = "cpu"

from tpuvsr.engine.paged_bfs import PagedBFS          # noqa: E402
from tpuvsr.engine.spec import load_spec              # noqa: E402

seconds = float(sys.argv[1]) if len(sys.argv) > 1 else 1500.0
tile = int(sys.argv[2]) if len(sys.argv) > 2 else 512
chunk_tiles = int(sys.argv[3]) if len(sys.argv) > 3 else 32

CKPT = os.path.join(REPO, "scripts", "shipped_ckpt")
OUT = os.path.join(REPO, "scripts", "shipped_pin.json")

REF = os.environ.get(
    "TPUVSR_REFERENCE", "/root/reference/vsr-revisited/paper")
spec = load_spec(f"{REF}/VSR.tla", f"{REF}/VSR.cfg")
assert spec.symmetry_perms, "shipped VSR.cfg declares SYMMETRY"

t0 = time.time()
eng = PagedBFS(spec, tile_size=tile, chunk_tiles=chunk_tiles,
               next_capacity=1 << 17, fpset_capacity=1 << 24)
from tpuvsr.engine.checkpoint import prior_elapsed  # noqa: E402

resume = CKPT if os.path.isdir(CKPT) else None
prev_elapsed = prior_elapsed(CKPT) if resume else 0.0
if resume:
    print(f"[shipped] resuming from {CKPT}", flush=True)
res = eng.run(max_seconds=prev_elapsed + seconds, resume_from=resume,
              checkpoint_path=CKPT, checkpoint_every=120.0,
              log=lambda m: print(f"[shipped] {m}", flush=True))
elapsed = res.elapsed
out = {
    "config": "VSR.cfg UNCHANGED (R=3, C=1, |Values|=2, timer=2, "
              "restarts=0, SYMMETRY ON, AcknowledgedWriteNotLost)",
    "engine": "paged",
    "backend": backend,
    "symmetry_perms": len(spec.symmetry_perms),
    "window_s": seconds,
    "tile": tile,
    "elapsed_s": round(elapsed, 1),
    "depth_reached": res.diameter,
    "distinct_states": res.distinct_states,
    "states_generated": res.states_generated,
    "distinct_per_s": round(res.distinct_states / max(elapsed, 1e-9),
                            1),
    "fixpoint": res.error is None,
    "level_sizes_tail": eng.level_sizes[-8:],
    "n_levels": len(eng.level_sizes),
    "violated": res.violated_invariant,
    "error": res.error,
    "ok": res.ok,
}
with open(OUT, "w") as f:
    json.dump(out, f, indent=1)
print(json.dumps(out))
