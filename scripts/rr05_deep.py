"""Deepen the RR05 bounded pin with TPU throughput (VERDICT r4 item 8).

r4 pinned VR_REPLICA_RECOVERY (CrashLimit=1, |Values|=1, timer=1) to a
BOUNDED oracle: 12,749,898 distinct at depth 189, frontier still
growing ~130k/level at the cutoff (~6h of 1-core CPU) — the one corpus
module with neither a fixpoint nor a full-space differential
(scripts/recovery_fixpoints.json).  This script re-runs the space
through the PAGED engine in resumable wall-clock windows: each run
extends the previous one via the level-boundary checkpoint
(scripts/rr05_ckpt), records the exact per-level prefix, and asserts it
matches the r4 prefix where they overlap (the levels are an exact
oracle; any divergence is an engine regression, not progress).

Writes scripts/rr05_deep.json.

Usage: [TPUVSR_TPU=1] python scripts/rr05_deep.py [seconds] [tile] [chunk_tiles]
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tpuvsr.platform_select import ensure_backend

backend = ensure_backend(log=lambda m: print(f"[rr05] {m}", flush=True))

from tpuvsr.engine.paged_bfs import PagedBFS          # noqa: E402

seconds = float(sys.argv[1]) if len(sys.argv) > 1 else 1500.0
tile = int(sys.argv[2]) if len(sys.argv) > 2 else 512
chunk_tiles = int(sys.argv[3]) if len(sys.argv) > 3 else 32

CKPT = os.path.join(REPO, "scripts", "rr05_ckpt")
OUT = os.path.join(REPO, "scripts", "rr05_deep.json")

sys.path.insert(0, os.path.join(REPO, "scripts"))
_argv, sys.argv = sys.argv, sys.argv[:1]
from pin_fixpoints import RECOVERY_CFG, load          # noqa: E402
sys.argv = _argv

spec = load("05-replica-recovery/VR_REPLICA_RECOVERY", RECOVERY_CFG,
            None)

t0 = time.time()
eng = PagedBFS(spec, tile_size=tile, chunk_tiles=chunk_tiles,
               next_capacity=1 << 17, fpset_capacity=1 << 24)
from tpuvsr.engine.checkpoint import prior_elapsed  # noqa: E402

resume = CKPT if os.path.isdir(CKPT) else None
prev_elapsed = prior_elapsed(CKPT) if resume else 0.0
if resume:
    print(f"[rr05] resuming from {CKPT}", flush=True)
res = eng.run(max_seconds=prev_elapsed + seconds, resume_from=resume,
              checkpoint_path=CKPT, checkpoint_every=120.0,
              log=lambda m: print(f"[rr05] {m}", flush=True))
elapsed = res.elapsed

# cross-check the completed-level prefix against the r4 bounded pin
prefix_ok = None
try:
    with open(os.path.join(REPO, "scripts",
                           "recovery_fixpoints.json")) as f:
        r4 = json.load(f)["VR_REPLICA_RECOVERY"]["single_bounded"]
    want = r4.get("level_sizes")
    if want:
        done = eng.level_sizes[:-1]  # last level may be partial
        overlap = min(len(done), len(want))
        prefix_ok = done[:overlap] == [int(x) for x in want[:overlap]]
except (OSError, KeyError, ValueError):
    pass

out = {
    "module": "VR_REPLICA_RECOVERY (RR05), CrashLimit=1, |Values|=1, "
              "timer=1",
    "engine": "paged",
    "backend": backend,
    "window_s": seconds,
    "tile": tile,
    "chunk_tiles": chunk_tiles,
    "elapsed_s": round(elapsed, 1),
    "depth_reached": res.diameter,
    "distinct_states": res.distinct_states,
    "states_generated": res.states_generated,
    "distinct_per_s": round(res.distinct_states / max(elapsed, 1e-9),
                            1),
    "fixpoint": res.error is None,
    "r4_bounded_pin": {"distinct": 12749898, "depth": 189},
    "beats_r4_pin": res.distinct_states > 12749898
    or res.error is None,
    "prefix_matches_r4": prefix_ok,
    "level_sizes_tail": eng.level_sizes[-10:],
    "n_levels": len(eng.level_sizes),
    "violated": res.violated_invariant,
    "error": res.error,
    "ok": res.ok,
}
with open(OUT, "w") as f:
    json.dump(out, f, indent=1)
print(json.dumps(out))
