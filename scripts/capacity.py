"""Defect-config BFS capacity analysis (SURVEY.md §7.3.8; VERDICT r2
missing #6): measure bytes/state and FPSet cost from the actual dense
layout, project HBM needs at defect scale, and write CAPACITY.md.

Usage: python scripts/capacity.py
"""

import json
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tpuvsr.platform_select import force_cpu
if os.environ.get("TPUVSR_TPU") != "1":
    force_cpu()

from tpuvsr.engine.spec import SpecModel
from tpuvsr.frontend.cfg import parse_cfg_file
from tpuvsr.frontend.parser import parse_module_file
from tpuvsr.models.vsr import VSRCodec

REFERENCE = os.environ.get(
    "TPUVSR_REFERENCE", "/root/reference/vsr-revisited/paper")

mod = parse_module_file(f"{REFERENCE}/VSR.tla")
cfg = parse_cfg_file(f"{REPO}/examples/VSR_defect.cfg")
spec = SpecModel(mod, cfg)


def state_bytes(max_msgs):
    codec = VSRCodec(spec.ev.constants, max_msgs=max_msgs)
    z = codec.zero_state()
    per = {k: int(np.prod(np.shape(v)) or 1) * 4 for k, v in z.items()}
    # packed bit-planed row (ISSUE 9): the at-rest/wire format the
    # device engines default to, sized by the widths-pass ranges
    from tpuvsr.analysis.passes.widths import derive_ranges_from
    from tpuvsr.engine.pack import build_pack_spec
    pk = build_pack_spec(
        codec, ranges=derive_ranges_from(spec.ev.constants, "VSR"))
    return sum(per.values()), per, codec.shape, pk


HBM_PER_CHIP = 16 << 30          # v5e
CHIPS = 8
FP_SLOT_BYTES = 20               # [cap, 5] uint32 FPSet slot
LOAD = 0.5                       # max healthy FPSet load factor

rows = []
for M in (48, 64, 96, 128):
    sb, per, shape, pk = state_bytes(M)
    rows.append((M, sb, pk.packed_bytes, pk.ratio))

sb48, per48, shape48, pk48 = state_bytes(48)
pb48 = pk48.packed_bytes

fp_cap_total = int(CHIPS * HBM_PER_CHIP * 0.5 / FP_SLOT_BYTES * LOAD)

out = f"""# CAPACITY — defect-config BFS sizing (VSR.tla, R=3, |Values|=3, timer=3)

Derived from the actual dense layout (`models/vsr.py` `zero_state`)
for the defect fixture (`examples/VSR_defect.cfg`); reference baseline:
multiple days + >=500 GB disk on a large CPU box
(/root/reference/README.md:20).

## Bytes per state: dense planes vs the packed bit-planed row

(dense = int32 struct-of-arrays, one word per field; packed = the
`engine/pack.py` interchange format the device engines default to —
per-field bit budgets from the speclint widths pass, `-pack off`
restores dense)

| MAX_MSGS | dense bytes/state | packed bytes/state | ratio |
|---|---|---|---|
""" + "\n".join(f"| {m} | {b:,} | {p:,} | {r:.2f}x |"
                for m, b, p, r in rows) + f"""

Top contributors at MAX_MSGS=48 (bytes):
""" + "\n".join(f"- `{k}`: {v:,}"
                for k, v in sorted(per48.items(), key=lambda kv: -kv[1])[:6])
out += f"""

Shapes: R={shape48.R}, V={shape48.V}, MAX_OPS={shape48.MAX_OPS},
MAX_VIEW={shape48.MAX_VIEW}.

## HBM budget on a v5e-8 (16 GB/chip x 8)

- **Fingerprints**: 20 B/slot (claim word + 128-bit fp).  At <= {LOAD:.0%}
  load with half of HBM given to the FPSet, the 8-chip mesh holds
  ~**{fp_cap_total / 1e9:.1f} B distinct states** — fingerprint capacity is
  NOT the binding constraint at defect scale (TLC burned 500 GB of disk
  largely on queue/state storage, not fingerprints).
- **Frontier**: the binding constraint — now measured at the PACKED
  row size ({pb48} B/state at MAX_MSGS=48, {pk48.ratio:.1f}x denser
  than the {sb48 / 1024:.1f} KiB dense row): one chip's spare ~6 GB
  holds ~**{6e9 / pb48 / 1e6:.1f} M frontier states**
  ({CHIPS * 6e9 / pb48 / 1e6:.0f} M mesh-wide) vs
  {6e9 / sb48 / 1e6:.1f} M dense; the same factor multiplies paged
  spill bandwidth and the sharded exchange.  Remaining mitigations:
  1. **BUILT (r4)**: `engine/paged_bfs.py` pages the frontier through
     host RAM — with packing the 125 GB host holds ~{125e9 / pb48 / 1e6:.0f} M
     states ({125e9 / sb48 / 1e6:.0f} M dense);
  2. bag-slot compression, RE-SCOPED: packing already shrinks the log
     planes ~16x (an entry packs to 8 bits vs 128 dense), so a
     content-addressed side table of distinct logs now buys only the
     residual duplicate-content factor, not the raw
     {per48['m_log'] / sb48:.0%} the dense m_log plane suggested —
     it drops below the DCN tier in priority;
  3. sharding the frontier over more hosts (DCN tier).
- **Trace pointers**: 10 B/state on host; 1e9 states = 10 GB host RAM
  (the 125 GB host holds ~12 B states).

## Measured throughput anchors

(From `BENCH_*.json` / `scripts/hunt_result.json` where available; the
flagship BFS to the violation needs both a frontier-paging tier and a
TPU-backend run, neither of which this round's dead TPU tunnel allowed
— the numbers below are CPU-backend anchors.)
"""

bench_path = os.path.join(REPO, "BENCH_r02.json")
if os.path.exists(bench_path):
    with open(bench_path) as f:
        b = json.load(f).get("parsed", {})
    out += (f"\n- round-2 shrunken-flagship BFS: "
            f"{b.get('value')} distinct/s, "
            f"{b.get('generated_per_s')} generated/s "
            f"({b.get('backend')}).\n")
hunt_path = os.path.join(REPO, "scripts", "hunt_result.json")
if os.path.exists(hunt_path):
    with open(hunt_path) as f:
        h = json.load(f)
    out += (f"- guided-simulation time-to-violation on the defect "
            f"fixture: {h.get('time_to_violation_s')} s "
            f"({h.get('backend')}, {h.get('walkers')} walkers, "
            f"seed {h.get('seed')}).\n")

out += """
## Projection to the <1 h north star (v5e-8)

The exhaustive-BFS route needs ~1e9-1e10 distinct states (unmeasured —
TLC's 500 GB disk / multi-day run bounds it loosely from above) at
>=3 M distinct/s sustained to finish inside an hour; fingerprint
capacity supports it, frontier paging is the engineering risk.  The
simulation route (the reference's own recommendation, README:22) needs
no FPSet at all and parallelizes perfectly: the guided
importance-splitting hunt already reproduces the violation on CPU (see
anchor above when present); on a v5e-8 the same walker program scales
~linearly with lane count x clock, putting time-to-violation well
under the hour target.
"""

with open(os.path.join(REPO, "CAPACITY.md"), "w") as f:
    f.write(out)
print(out)
