"""Liveness verdicts at (or toward) the SHIPPED analysis-cfg constants
(VERDICT r4 item 5).

The reference's shipped cfgs run ConvergenceToView /
OpEventuallyAllOrNothing at R=3, |Values|=2, StartViewOnTimerLimit=2
(analysis/01-view-changes/*.cfg).  The r5 size probe
(scripts/a01_shipped_probe.json) measured that space past 4.2M
distinct at depth 14 with the frontier still growing 1.9x/level —
projected well past 1e8 states, beyond a resident behavior graph on
this host.  So this script supports BOTH: the shipped cfg unchanged
(an honest bounded attempt, reported as such when the cap trips) and
intermediate constant ladders (|V|=2/timer=1, |V|=1/timer=2 — each
strictly larger than the r4 toy |V|=1/timer=1 verdicts) that complete
to real verdicts.  Pipeline: paged-BFS enumeration -> device-built
behavior graph (CSR edges, gid-valued FPSet) -> device-compiled
property leaves (lower/compile) -> host fair-SCC.

Writes/merges scripts/liveness_shipped.json.

Usage: [TPUVSR_TPU=1] python scripts/liveness_shipped.py [a01|i01]
           [max_states] [tile] [chunk_tiles] [values] [timer]
(values/timer override the shipped constants when given)
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tpuvsr.platform_select import ensure_backend, force_cpu

if os.environ.get("TPUVSR_TPU") == "1":
    backend = ensure_backend(log=lambda m: print(f"[liveness] {m}",
                                                 flush=True))
else:
    force_cpu()
    backend = "cpu"

from tpuvsr.engine.device_liveness import DeviceGraph   # noqa: E402
from tpuvsr.engine.liveness import liveness_check       # noqa: E402
from tpuvsr.engine.spec import load_spec                # noqa: E402

MODS = {
    "a01": "VR_ASSUME_NEWVIEWCHANGE",
    "i01": "VR_INC_RESEND",
}

which = sys.argv[1] if len(sys.argv) > 1 else "a01"
max_states = int(sys.argv[2]) if len(sys.argv) > 2 else 30_000_000
tile = int(sys.argv[3]) if len(sys.argv) > 3 else 512
chunk_tiles = int(sys.argv[4]) if len(sys.argv) > 4 else 16
values = int(sys.argv[5]) if len(sys.argv) > 5 else None
timer = int(sys.argv[6]) if len(sys.argv) > 6 else None

REF = os.environ.get(
    "TPUVSR_REFERENCE", "/root/reference/vsr-revisited/paper")
stem = f"{REF}/analysis/01-view-changes/{MODS[which]}"
spec = load_spec(f"{stem}.tla", f"{stem}.cfg")
key = which
desc = f"{MODS[which]}.cfg UNCHANGED (R=3, |Values|=2, timer=2)"
if values is not None or timer is not None:
    from tpuvsr.core.values import ModelValue
    from tpuvsr.engine.spec import SpecModel
    from tpuvsr.frontend.cfg import parse_cfg_file
    from tpuvsr.frontend.parser import parse_module_file
    mod = parse_module_file(f"{stem}.tla")
    cfg = parse_cfg_file(f"{stem}.cfg")
    if values is not None:
        cfg.constants["Values"] = frozenset(
            ModelValue(f"v{i + 1}") for i in range(values))
    if timer is not None:
        cfg.constants["StartViewOnTimerLimit"] = timer
    spec = SpecModel(mod, cfg)
    v = values if values is not None else 2
    t = timer if timer is not None else 2
    key = f"{which}-v{v}t{t}"
    desc = (f"{MODS[which]}.cfg with |Values|={v}, timer={t} "
            f"(intermediate ladder toward the shipped constants)")

OUT = os.path.join(REPO, "scripts", "liveness_shipped.json")
results = {}
if os.path.exists(OUT):
    with open(OUT) as f:
        results = json.load(f)

entry = {
    "module": MODS[which],
    "config": desc + " — SPECIFICATION LivenessSpec",
    "backend": backend,
    "tile": tile,
    "properties": list(spec.temporal_props),
}
t0 = time.time()
try:
    g = DeviceGraph(spec, tile_size=tile, chunk_tiles=chunk_tiles,
                    max_states=max_states,
                    fpset_capacity=1 << 24, next_capacity=1 << 17,
                    # pre-sized expansion caps: the timer=2 SVC storm
                    # overflows the default x2 caps and every growth
                    # is a multi-minute recompile
                    expand_mult=4,
                    log=lambda m: print(f"[liveness] {m}", flush=True))
    entry.update({
        "states": g.n,
        "edges": int(g.csr[1].shape[0]),
        "graph_build_s": round(g.build_elapsed, 1),
        "bfs_s": round(g.bfs_elapsed, 1),
    })
    res = liveness_check(spec, graph=g,
                         log=lambda m: print(f"[liveness] {m}",
                                             flush=True))
    entry.update({
        "ok": res.ok,
        "violated_property": res.property_name,
        "check_s": round(res.elapsed, 1),
        "error": res.error,
        "verdict": ("all temporal properties hold" if res.ok
                    else f"violated: {res.property_name}"),
    })
except Exception as e:  # noqa: BLE001
    entry["error"] = f"{type(e).__name__}: {e}"
entry["total_s"] = round(time.time() - t0, 1)
results[key] = entry
with open(OUT, "w") as f:
    json.dump(results, f, indent=1)
print(json.dumps(entry))
