"""BASELINE configs[2] scale simulation: 1e6 random walks x depth 100
(TLC-uniform successor sampling, invariants checked every step) — on
the SHARDED WALKER FLEET (tpuvsr/sim, ISSUE 7; previously the
single-device DeviceSimulator scan loop, BENCH_r03: 17.7 walks/s).

Runs as many walks of the target shape as the time budget allows and
records measured walks/s + the projected wall clock for the full 1e6
— honest about backend and completion.  The fleet's per-(seed,
walk-id) determinism means the walk population is identical at any
walker count, so rounds at 131072 walkers measure the same workload
BENCH_r03 measured at 4096.  Writes scripts/<out> (arg 4, default
sim_scale.json).

Usage: python scripts/sim_scale.py [walkers] [max_seconds] [num_walks] [out.json]
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tpuvsr.platform_select import force_cpu
if os.environ.get("TPUVSR_TPU") != "1":
    force_cpu()

walkers = int(sys.argv[1]) if len(sys.argv) > 1 else 1 << 17
max_seconds = float(sys.argv[2]) if len(sys.argv) > 2 else 900
num = int(sys.argv[3]) if len(sys.argv) > 3 else 10**6
out_name = sys.argv[4] if len(sys.argv) > 4 else "sim_scale.json"

from tpuvsr.engine.spec import SpecModel
from tpuvsr.frontend.cfg import parse_cfg_file
from tpuvsr.frontend.parser import parse_module_file
from tpuvsr.sim.fleet import FleetSimulator

REFERENCE = os.environ.get(
    "TPUVSR_REFERENCE", "/root/reference/vsr-revisited/paper")

mod = parse_module_file(f"{REFERENCE}/VSR.tla")
cfg = parse_cfg_file(f"{REPO}/examples/VSR_defect.cfg")
spec = SpecModel(mod, cfg)

import jax
backend = jax.default_backend()
print(f"backend: {backend} ({len(jax.devices())} device(s))",
      file=sys.stderr, flush=True)

# reuse the previous run's calibrated dispatch-group caps (same walker
# count) so the measurement starts at steady state instead of paying
# the cap-growth recompiles inside the budget
prev_caps = None
prev_path = os.path.join(REPO, "scripts", "sim_scale.json")
if os.path.exists(prev_path):
    try:
        with open(prev_path) as f:
            prev = json.load(f)
        if prev.get("walkers") == walkers and prev.get("group_caps"):
            prev_caps = list(prev["group_caps"])
    except ValueError:
        pass

sim = FleetSimulator(spec, walkers=walkers, chunk_steps=25,
                     max_msgs=64, group_caps=prev_caps)
t0 = time.time()
res = sim.run(num=num, depth=100, seed=0, max_seconds=max_seconds,
              log=lambda m: print(f"sim: {m} ({time.time()-t0:.0f}s)",
                                  file=sys.stderr, flush=True))
el = res.elapsed
walks_per_s = res.walks / el if el > 0 else 0.0
out = {
    "target": {"num_walks": num, "depth": 100,
               "config": "VSR defect fixture (R=3, |Values|=3, timer=3)"},
    "engine": "fleet-sim",
    "walkers": walkers,
    "mesh_devices": sim.D,
    "split_enabled": False,
    "walks_completed": res.walks,
    "steps": res.steps,
    "elapsed_s": round(el, 1),
    "walks_per_s": round(walks_per_s, 2),
    "steps_per_s": round(res.steps / el, 1) if el > 0 else 0.0,
    "projected_s_for_1e6_walks": round(10**6 / walks_per_s, 1)
    if walks_per_s else None,
    "completed_target": res.walks >= num,
    "vs_bench_r03_17_7": round(walks_per_s / 17.7, 2)
    if walks_per_s else None,
    "ok": res.ok,
    "violated": res.violated_invariant,
    "backend": backend,
    "dispatch": sim.dispatch,
    "group_caps": list(sim.group_caps),
}
print(json.dumps(out))
with open(os.path.join(REPO, "scripts", out_name), "w") as f:
    json.dump(out, f, indent=1)
