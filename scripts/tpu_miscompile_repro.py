"""On-hardware repro ladder for the tile-1024 axon mis-exploration.

r4's tile sweep found DeviceBFS at tile=1024 on the tunneled v5e
produces 58,957 distinct states on the flagship small config vs the
pinned 43,941 — duplicate states entering the frontier — while
tile<=512 matches exactly (scripts/tile_sweep.json; the same engine is
exact at every width on CPU).  This script isolates WHERE the TPU
lowering diverges, cheapest hypothesis first, writing partial results
to scripts/miscompile_repro.json after every stage so a tunnel flap
never loses evidence (completed stages are skipped on re-run):

  insert       synthetic duplicate-heavy batches through insert_core
               chained in a fori_loop (the level kernel's composition):
               fresh-count must equal the distinct count, the table
               must hold exactly the expected fingerprints (a torn
               claim scatter leaves garbage slots).
  insert_barrier  same, in a subprocess with TPUVSR_FPSET_BARRIER=1
               (an optimization_barrier between the claim scatter and
               the verify gather) — only when `insert` failed.
  fingerprint  width-determinism of the canonical fingerprint: the
               same reachable states fingerprinted at batch widths
               1024/2048 must match the width-256 values (width-
               dependent vectorization would make one state hash two
               ways, which also duplicates frontier entries).
  levels       DeviceBFS tile=1024 chunked run vs the pinned per-level
               sizes (scripts/pinned_levels_small.json): the first
               divergent BFS level localizes the failure in time.
  levels_full  same at hash_mode="full" — if full-state hashing is
               exact where incremental diverges, the incremental
               fingerprint path is the culprit.
  levels_barrier  tile=1024 with the claim barrier — if exact, the
               insert claim race is the culprit and the barrier is the
               fix.

Usage: [TPUVSR_TPU=1] python scripts/tpu_miscompile_repro.py [stage ...]
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# diagnosis runs need the unvalidated width the guard refuses
os.environ.setdefault("TPUVSR_UNSAFE_TILE", "1")

from tpuvsr.platform_select import ensure_backend, force_cpu  # noqa: E402

if os.environ.get("TPUVSR_TPU") == "1":
    backend = ensure_backend(log=lambda m: print(f"[repro] {m}",
                                                 flush=True))
else:
    force_cpu()
    backend = "cpu"

OUT = os.environ.get(
    "TPUVSR_REPRO_OUT", os.path.join(REPO, "scripts",
                                     "miscompile_repro.json"))
BUDGET = float(os.environ.get("TPUVSR_REPRO_BUDGET", "3300"))
T0 = time.time()

RESULTS = {}
if os.path.exists(OUT):
    try:
        with open(OUT) as f:
            RESULTS = json.load(f)
    except ValueError:
        RESULTS = {}
RESULTS["backend"] = backend


def save():
    with open(OUT, "w") as f:
        json.dump(RESULTS, f, indent=1)


def left():
    return BUDGET - (time.time() - T0)


def log(msg):
    print(f"[repro] {msg}", flush=True)


# ----------------------------------------------------------------------
def stage_insert(widths=(512, 1024, 2048, 4096), rounds=8, seed=0):
    import numpy as np
    import jax
    import jax.numpy as jnp
    from tpuvsr.engine.fpset import empty_table, insert_core

    rows = []
    for B in widths:
        rng = np.random.default_rng(seed + B)
        P = max(64, rounds * B // 2)
        pool = rng.integers(1, 2**32, size=(P, 4), dtype=np.uint32)
        pool[:, 3] = np.arange(P, dtype=np.uint32)   # rows distinct
        idx = rng.integers(0, P, size=(rounds, B))
        batches = jnp.asarray(pool[idx])
        n_unique = int(np.unique(idx).size)
        cap = 1 << max(12, int(np.ceil(np.log2(P * 4))))
        slots0 = empty_table(cap)["slots"]

        @jax.jit
        def run(slots, batches):
            def body(i, carry):
                slots, fresh, ovf = carry
                tbl, fr, o = insert_core(
                    {"slots": slots}, batches[i],
                    jnp.ones((batches.shape[1],), bool))
                return (tbl["slots"],
                        fresh + fr.sum(dtype=jnp.int32), ovf | o)
            return jax.lax.fori_loop(
                0, batches.shape[0], body,
                (slots, jnp.asarray(0, jnp.int32), jnp.asarray(False)))

        t0 = time.time()
        slots, fresh, ovf = jax.device_get(run(slots0, batches))
        occ = slots[slots[:, 0] != 0]
        keyed = pool.copy()
        keyed[keyed[:, 0] == 0, 0] = 1
        expect = set(map(tuple, keyed[np.unique(idx)]))
        got = set(map(tuple, occ[:, :4].astype(np.uint32)))
        row = {
            "width": B, "rounds": rounds, "unique": n_unique,
            "fresh": int(fresh), "occupied": int(occ.shape[0]),
            "overflow": bool(ovf),
            "garbage_slots": len(got - expect),
            "missing_fps": len(expect - got),
            "elapsed_s": round(time.time() - t0, 1),
        }
        row["ok"] = (row["fresh"] == n_unique and not row["overflow"]
                     and row["garbage_slots"] == 0
                     and row["missing_fps"] == 0)
        rows.append(row)
        log(f"insert width={B}: fresh={row['fresh']} want={n_unique} "
            f"garbage={row['garbage_slots']} ok={row['ok']}")
    return rows


# ----------------------------------------------------------------------
def _collect_states(n_target=3072):
    """Reachable dense states of the flagship small config, enumerated
    through the kernel's own step_batch (width 256 — a validated
    width)."""
    import numpy as np
    from __graft_entry__ import _small_spec
    from tpuvsr.models import registry

    spec = _small_spec()
    codec, kern = registry.make_model(spec)
    init = [codec.encode(st) for st in spec.init_states()]
    states = [{k: np.asarray(v) for k, v in init[0].items()}]
    seen = set()
    frontier = list(states)
    W = 256
    while len(states) < n_target and frontier:
        chunk = frontier[:W]
        frontier = frontier[W:]
        cs = chunk + [chunk[-1]] * (W - len(chunk))
        batch = {k: np.stack([d[k] for d in cs]) for k in cs[0]}
        succs, en = kern.step_batch(batch)
        en = np.asarray(en)
        succs = {k: np.asarray(v) for k, v in succs.items()
                 if not k.startswith("_")}
        for i in range(len(chunk)):
            for lane in np.nonzero(en[i])[0]:
                d = {k: succs[k][i, lane] for k in succs}
                if int(d["err"]) != 0:
                    continue
                key = b"".join(np.ascontiguousarray(d[k]).tobytes()
                               for k in sorted(d))
                if key in seen:
                    continue
                seen.add(key)
                states.append(d)
                frontier.append(d)
                if len(states) >= n_target:
                    break
            if len(states) >= n_target:
                break
    return kern, states


def stage_fingerprint(widths=(1024, 2048), ref_width=256):
    import numpy as np
    kern, states = _collect_states()
    log(f"fingerprint: {len(states)} reachable states collected")

    def fps_at(width):
        out = []
        for off in range(0, len(states), width):
            chunk = states[off:off + width]
            cs = chunk + [chunk[-1]] * (width - len(chunk))
            batch = {k: np.stack([d[k] for d in cs]) for k in cs[0]}
            f = np.asarray(kern.fingerprint_batch(batch))
            out.append(f[:len(chunk)])
        return np.concatenate(out)

    ref = fps_at(ref_width)
    rows = []
    for w in widths:
        got = fps_at(w)
        bad = np.nonzero((got != ref).any(axis=1))[0]
        rows.append({"width": w, "states": len(states),
                     "mismatches": int(bad.size),
                     "first_bad_index": int(bad[0]) if bad.size else None,
                     "ok": bad.size == 0})
        log(f"fingerprint width={w}: {bad.size} mismatches vs "
            f"width-{ref_width}")
    return {"ref_width": ref_width, "rows": rows}


# ----------------------------------------------------------------------
def stage_levels(tile=1024, hash_mode="incremental"):
    from __graft_entry__ import _small_spec
    from tpuvsr.engine.device_bfs import DeviceBFS

    with open(os.path.join(REPO, "scripts",
                           "pinned_levels_small.json")) as f:
        pinned = json.load(f)
    want = pinned["level_sizes"]
    spec = _small_spec()
    eng = DeviceBFS(spec, tile_size=tile, fpset_capacity=1 << 21,
                    next_capacity=1 << 15, expand_mult=2,
                    hash_mode=hash_mode,
                    expand_mults={"ReceiveMatchingSVC": 4, "SendDVC": 4})
    t0 = time.time()
    res = eng.run()
    lv = [int(x) for x in eng.level_sizes]
    first_div = next((i for i, (a, b) in enumerate(zip(lv, want))
                      if a != b), None)
    if first_div is None and len(lv) != len(want):
        first_div = min(len(lv), len(want))
    row = {
        "tile": tile, "hash_mode": hash_mode,
        "distinct": res.distinct_states,
        "generated": res.states_generated,
        "pinned_distinct": pinned["distinct"],
        "elapsed_s": round(time.time() - t0, 1),
        "level_sizes": lv,
        "first_divergent_level": first_div,
        "ok": res.distinct_states == pinned["distinct"]
        and first_div is None,
    }
    log(f"levels tile={tile} hash={hash_mode}: distinct="
        f"{res.distinct_states} (pinned {pinned['distinct']}), first "
        f"divergent level {first_div}")
    return row


# ----------------------------------------------------------------------
def run_subprocess(stage, out_suffix, extra_env):
    sub_out = OUT.replace(".json", f"_{out_suffix}.json")
    if os.path.exists(sub_out):
        os.unlink(sub_out)
    env = dict(os.environ)
    env.update(extra_env)
    env["TPUVSR_REPRO_OUT"] = sub_out
    env["TPUVSR_REPRO_BUDGET"] = str(max(60, int(left()) - 30))
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), stage],
            env=env, cwd=REPO, timeout=max(120, left()))
        rc = r.returncode
    except subprocess.TimeoutExpired:
        rc = "timeout"
    if os.path.exists(sub_out):
        with open(sub_out) as f:
            rec = json.load(f).get(stage)
        if rec is not None:
            return rec
    # an error dict (never None): _errored() then re-attempts the
    # stage on the next queue run instead of suppressing it forever
    return {"error": f"subprocess rc={rc}, no stage output"}


def _errored(rec):
    return isinstance(rec, dict) and "error" in rec


def main():
    stages = sys.argv[1:] or ["insert", "fingerprint", "levels"]
    for st in stages:
        if st in RESULTS and not _errored(RESULTS[st]):
            log(f"stage {st}: already recorded, skipping")
            continue
        if left() < 120:
            log(f"stage {st}: budget exhausted, stopping")
            break
        log(f"=== stage {st} (budget left {left():.0f}s)")
        try:
            if st == "insert":
                RESULTS[st] = stage_insert()
            elif st == "fingerprint":
                RESULTS[st] = stage_fingerprint()
            elif st == "levels":
                RESULTS[st] = stage_levels()
            elif st == "levels_full":
                RESULTS[st] = stage_levels(hash_mode="full")
            else:
                log(f"unknown stage {st}")
                continue
        except Exception as e:  # noqa: BLE001
            RESULTS[st] = {"error": f"{type(e).__name__}: {e}"}
        save()

    # conditional follow-ups (skipped when already recorded)
    def _want(key):
        return key not in RESULTS or _errored(RESULTS[key])

    ins = RESULTS.get("insert")
    insert_bad = isinstance(ins, list) and any(not r["ok"] for r in ins)
    if insert_bad and _want("insert_barrier") and left() > 300:
        log("=== stage insert_barrier (insert failed; testing the "
            "claim-barrier hypothesis)")
        RESULTS["insert_barrier"] = run_subprocess(
            "insert", "barrier", {"TPUVSR_FPSET_BARRIER": "1"})
        save()

    lv = RESULTS.get("levels")
    levels_bad = isinstance(lv, dict) and not lv.get("ok", True)
    if levels_bad and _want("levels_full") and left() > 900:
        log("=== stage levels_full (incremental diverged; "
            "discriminating the fingerprint path)")
        try:
            RESULTS["levels_full"] = stage_levels(hash_mode="full")
        except Exception as e:  # noqa: BLE001
            RESULTS["levels_full"] = {"error": f"{type(e).__name__}: {e}"}
        save()
    if levels_bad and _want("levels_barrier") and left() > 900:
        log("=== stage levels_barrier (end-to-end with the claim "
            "barrier)")
        RESULTS["levels_barrier"] = run_subprocess(
            "levels", "barrier2", {"TPUVSR_FPSET_BARRIER": "1"})
        save()

    save()
    print(json.dumps(RESULTS))


if __name__ == "__main__":
    main()
