"""Headline benchmark: distinct states/sec of the device BFS engine on
the shrunken flagship config (BASELINE.json configs[0]: VSR.tla with
R=3, C=1, Values={v1}, StartViewOnTimerLimit=1 — 43,941 distinct
states, diameter 24), checked to fixpoint.

Prints ONE JSON line {metric, value, unit, vs_baseline, ...}.
vs_baseline = device distinct states/sec over the single-thread
interpreter oracle's distinct states/sec on the same spec (the stand-in
for the reference's explicit-state checker; the reference publishes no
throughput figures — SURVEY.md §6).

Robustness (round-1 failure modes):
* the metric JSON is ALWAYS emitted — on SIGTERM/SIGINT, on an internal
  deadline short of the driver timeout, and on any crash — carrying
  whatever was measured so far plus a `phase` marker;
* the backend actually used is recorded in the JSON so a CPU-fallback
  run can't masquerade as a TPU number;
* the session TPU is reached through a tunnel that can hang backend
  init: the platform is probed in a subprocess with a timeout and the
  bench falls back to CPU if the tunnel is down;
* a missing reference mount no longer yields a dead phase-error doc:
  `_stub_round` measures the POR / symmetry / bounds levers on the
  in-repo stub fixtures instead (ISSUE 16 — cut ratios and verdict
  identities are exact there; throughput is honestly labeled
  useless and the perf gate is skipped).
"""

import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", "2400"))
INTERP_STATES = int(os.environ.get("BENCH_INTERP_STATES", "3000"))
T0 = time.time()
DEADLINE = T0 + 0.92 * BUDGET_S

# round-artifact attachments: key -> scripts/<file>.  Also the strip
# list for captured bench_tpu_run.json (anti-recursive-nesting) —
# bench_capture.py imports this, keep it the single source of truth.
ATTACHMENTS = (("defect_hunt", "hunt_result.json"),
               ("sim_scale", "sim_scale.json"),
               ("validate_demo", "validate_demo.json"),
               ("defect_bfs_window", "defect_window.json"),
               ("hunt_ablation", "hunt_ablation.json"),
               ("liveness_speedup", "liveness_speedup.json"),
               ("sim_scale_wide", "sim_scale_wide.json"),
               ("tpu_run", "bench_tpu_run.json"),
               ("tpu_tests", "tpu_tests.json"),
               ("tile_sweep", "tile_sweep.json"),
               ("multihost", "multihost.json"),
               ("recovery_fixpoints", "recovery_fixpoints.json"),
               # round-5 artifacts: the AST->kernel compiler's pinned
               # fixpoint, the occupancy-calibrated exchange ratio, the
               # tile-1024 miscompile repro ladder, shipped-constant
               # liveness/safety runs, and the RR05 deep pin
               ("compiled_kernel_fixpoint", "lower_fixpoint.json"),
               ("exchange_stats", "exchange_stats.json"),
               ("miscompile_repro", "miscompile_repro.json"),
               ("liveness_shipped", "liveness_shipped.json"),
               ("shipped_probe", "a01_shipped_probe.json"),
               ("shipped_pin", "shipped_pin.json"),
               ("rr05_deep", "rr05_deep.json"))

RESULT = {
    "metric": "VSR.tla BFS distinct states/sec (R=3, |Values|=1, timer=1)",
    "value": 0.0,
    "unit": "states/sec",
    "vs_baseline": 0.0,
    "backend": "unknown",
    "phase": "startup",
}
_EMITTED = False


def emit(code=0):
    global _EMITTED
    if not _EMITTED:
        _EMITTED = True
        print(json.dumps(RESULT), flush=True)
    if code is not None:
        os._exit(code)


def _on_signal(signum, frame):
    RESULT["phase"] += f" (signal {signum})"
    emit(1)


def _is_cpu_backend(b):
    return str(b).startswith("cpu")


def _perf_gate(result):
    """Diff this run's metrics against the newest BENCH_r*.json via
    scripts/compare_bench.py and embed the verdict (ISSUE 3 satellite:
    the perf gate rides the round driver's own artifact instead of
    needing a separate CI step).  Cross-backend comparisons (a
    cpu-fallback run against a TPU round, or vice versa) are marked
    advisory: ok=None."""
    import contextlib
    import glob
    import io
    import tempfile
    try:
        sys.path.insert(0, os.path.join(REPO, "scripts"))
        import compare_bench
        prev = sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json")))
        if not prev:
            return {"skipped": "no BENCH_r*.json baseline in the repo"}
        # the round driver wraps the bench RESULT under "parsed" (and
        # leaves it null when the last stdout line wasn't the metric
        # JSON) — walk newest-first for a round with a usable number
        baseline, base_doc = None, None
        for cand_path in reversed(prev):
            with open(cand_path) as f:
                doc = json.load(f)
            if isinstance(doc.get("parsed"), dict):
                doc = doc["parsed"]
            if compare_bench.throughput(doc, "distinct_per_s")[0] \
                    is not None:
                baseline, base_doc = cand_path, doc
                break
        if baseline is None:
            return {"skipped": "no BENCH_r*.json round carries a "
                               "usable distinct_per_s baseline"}
        pct = float(os.environ.get("BENCH_MAX_REGRESSION_PCT", "15"))
        cand = {k: result.get(k)
                for k in ("value", "metrics", "backend")}
        fd, cpath = tempfile.mkstemp(suffix=".json")
        with os.fdopen(fd, "w") as f:
            json.dump(cand, f)
        # the baseline may have been unwrapped from the driver's
        # "parsed" field — hand compare_bench the unwrapped doc
        fd, bpath = tempfile.mkstemp(suffix=".json")
        with os.fdopen(fd, "w") as f:
            json.dump(base_doc, f)
        buf = io.StringIO()
        try:
            with contextlib.redirect_stdout(buf), \
                    contextlib.redirect_stderr(buf):
                rc = compare_bench.main(
                    [bpath, cpath, "--max-regression", str(pct)])
        finally:
            os.unlink(cpath)
            os.unlink(bpath)
        same = (_is_cpu_backend(base_doc.get("backend", ""))
                == _is_cpu_backend(result.get("backend", "")))
        return {
            "baseline": os.path.basename(baseline),
            "baseline_backend": base_doc.get("backend"),
            "candidate_backend": result.get("backend"),
            "max_regression_pct": pct,
            "exit_code": rc,
            "ok": (rc == 0) if same else None,
            "advisory": not same,
            "detail": buf.getvalue().strip().splitlines()[:8],
        }
    except Exception as e:  # noqa: BLE001 — the gate never kills bench
        return {"error": f"{type(e).__name__}: {e}"}


def _probe_default_backend(timeout=180):
    """Can the session's default JAX platform initialize?  Run the probe
    in a subprocess: a dead TPU tunnel hangs backend init forever."""
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.default_backend())"],
            capture_output=True, text=True, timeout=timeout)
        if r.returncode == 0:
            return r.stdout.strip().splitlines()[-1]
    except subprocess.TimeoutExpired:
        pass
    return None


def main():
    backend = _probe_default_backend()
    if backend is None:
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        backend = "cpu-fallback (tpu tunnel unavailable)"
    import jax
    if backend.startswith("cpu"):
        jax.config.update("jax_platforms", "cpu")
    RESULT["backend"] = backend
    print(f"bench: backend = {backend}", file=sys.stderr)

    from __graft_entry__ import _small_spec
    from tpuvsr.engine.bfs import bfs_check
    from tpuvsr.engine.device_bfs import DeviceBFS

    # baseline: single-thread interpreter (exact TLC-style enumeration)
    RESULT["phase"] = "interpreter-baseline"
    try:
        spec = _small_spec()
    except OSError as e:
        # the reference corpus is not mounted, so the VSR.tla headline
        # cannot run; fall back to a measured stub-fixture round
        # (ISSUE 16) so the POR / symmetry / bounds levers still get
        # real A/B numbers instead of a dead phase-error doc
        print(f"bench: reference corpus unavailable ({e}); running "
              f"the stub-fixture lever round", file=sys.stderr)
        return _stub_round(str(e))
    base = bfs_check(spec, max_states=INTERP_STATES)
    base_sps = base.distinct_states / base.elapsed
    RESULT["baseline_interp_distinct_per_s"] = round(base_sps, 1)
    print(f"bench: interpreter baseline {base_sps:.0f} distinct/s "
          f"({base.states_generated / base.elapsed:.0f} generated/s)",
          file=sys.stderr)

    # device engine: compile+warm on a depth-limited run, then measure a
    # fresh full run on the SAME instance (jits are cached by closure)
    RESULT["phase"] = "compile"
    tile = int(os.environ.get("BENCH_TILE", "256"))
    # fused mode (default): whole fixpoint in O(1) dispatches — the
    # per-level host round-trips are the runtime on a tunneled TPU
    # (r4 first TPU run: 26.6 s for a 24-level space ~ 1.1 s/level)
    fused = os.environ.get("BENCH_FUSED", "1") != "0"
    RESULT["mode"] = "fused" if fused else "chunked"
    eng = DeviceBFS(spec, tile_size=tile, fpset_capacity=1 << 21,
                    next_capacity=1 << 15, expand_mult=2,
                    expand_mults={"ReceiveMatchingSVC": 4, "SendDVC": 4})
    runner = eng.run_fused if fused else eng.run
    t0 = time.time()
    runner(max_depth=6)
    compile_s = time.time() - t0
    RESULT["compile_s"] = round(compile_s, 1)
    print(f"bench: compile+warmup {compile_s:.1f}s", file=sys.stderr)

    # BENCH_SUPERVISE=1: run the timed headline through the resilience
    # supervisor (ISSUE 5 satellite) so a real TPU OOM degrades through
    # the tile ladder (and the paged fallback) instead of killing the
    # round; the supervisor outcome (attempts, degrades list,
    # resharded-from) lands in the round doc as RESULT["supervisor"]
    if os.environ.get("BENCH_SUPERVISE", "0") == "1" and not fused:
        from tpuvsr.engine.paged_bfs import PagedBFS
        from tpuvsr.resilience.supervisor import Supervisor
        sup = Supervisor(
            spec, engine="device", tile_size=tile,
            engine_factory=lambda kind, t:
                (PagedBFS if kind == "paged" else DeviceBFS)(
                    spec, tile_size=t, fpset_capacity=1 << 21,
                    next_capacity=1 << 15, expand_mult=2,
                    expand_mults={"ReceiveMatchingSVC": 4,
                                  "SendDVC": 4}),
            log=lambda m: print(f"bench: {m}", file=sys.stderr))

        def runner(**kw):
            kw.pop("log", None)     # the supervisor logs through its own
            r = sup.run(**kw)
            RESULT["supervisor"] = sup.summary()
            return r

    RESULT["phase"] = "device-bfs"
    t0 = time.time()
    res = runner(max_seconds=max(30.0, DEADLINE - time.time()),
                 log=lambda m: print(f"bench: {m}", file=sys.stderr))
    # self-check fires on a completed run that misses the pinned count,
    # AND on a partial (time-budget) run that OVERcounts — the space is
    # pinned complete, so distinct > 43941 is a mis-exploration even
    # when the run was cut short (ADVICE r4)
    if fused and (res.distinct_states != 43941 if res.error is None
                  else res.distinct_states > 43941):
        # self-check against the pinned fixpoint: a fused-pass
        # miscount must never become the graded number silently —
        # fall back to the chunked engine (tile-1024 precedent:
        # width-dependent TPU mis-exploration)
        RESULT["fused_mismatch_distinct"] = res.distinct_states
        RESULT["fused_mismatch_partial"] = res.error
        RESULT["mode"] = "chunked (fused self-check failed)"
        what = (f"{res.distinct_states} != 43941" if res.error is None
                else f"{res.distinct_states} > 43941 on a partial run "
                     f"({res.error})")
        print(f"bench: FUSED SELF-CHECK FAILED ({what}); falling back",
              file=sys.stderr)
        eng2 = DeviceBFS(spec, tile_size=tile, fpset_capacity=1 << 21,
                         next_capacity=1 << 15, expand_mult=2,
                         expand_mults={"ReceiveMatchingSVC": 4,
                                       "SendDVC": 4})
        eng2.run(max_depth=6)
        runner = eng2.run
        res = runner(max_seconds=max(30.0, DEADLINE - time.time()))
    dev_sps = res.states_generated / res.elapsed
    distinct_sps = res.distinct_states / res.elapsed
    RESULT.update({
        "phase": "done" if not res.error else f"partial: {res.error}",
        "value": round(distinct_sps, 1),
        "vs_baseline": round(distinct_sps / base_sps, 3),
        "distinct_states": res.distinct_states,
        "states_generated": res.states_generated,
        "diameter": res.diameter,
        "elapsed_s": round(res.elapsed, 2),
        "generated_per_s": round(dev_sps, 1),
        "reached_fixpoint": res.error is None,
        # tpuvsr-metrics/1 document of the timed run (phase timers,
        # counters, per-level trajectory) — BENCH_*.json files become
        # directly diffable via scripts/compare_bench.py
        "metrics": res.metrics,
    })
    # supervisor outcome + mesh identity (ISSUE 5): degrades list and
    # resharded-from make a degraded/resharded round self-describing;
    # compare_bench treats mesh-size mismatches as advisory
    g = (res.metrics or {}).get("gauges", {})
    RESULT.setdefault("supervisor", None)
    RESULT["mesh_devices"] = g.get("mesh_devices")
    RESULT["resharded_from"] = g.get("resharded_from")
    # packed-frontier identity (ISSUE 9): at-rest bytes one frontier
    # row costs the headline run and the dense/packed ratio;
    # compare_bench gates on bytes/state regressions (cross-layout
    # comparisons advisory, like pipeline depth)
    RESULT["frontier_bytes_per_state"] = g.get(
        "frontier_bytes_per_state")
    RESULT["pack_ratio"] = g.get("pack_ratio")
    # defect-layout sizing (the CAPACITY.md headline — derived from
    # the in-repo defect cfg, no reference mount needed): the ISSUE 9
    # acceptance anchor is a >=4x bytes/state cut at MAX_MSGS=48
    try:
        from tpuvsr.analysis.passes.widths import derive_ranges_from
        from tpuvsr.engine.pack import build_pack_spec
        from tpuvsr.frontend.cfg import parse_cfg_file
        from tpuvsr.models.vsr import VSRCodec
        dcfg = parse_cfg_file(os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "examples", "VSR_defect.cfg"))
        dpk = build_pack_spec(
            VSRCodec(dcfg.constants, max_msgs=48),
            ranges=derive_ranges_from(dcfg.constants, "VSR"))
        RESULT["defect_pack"] = {
            "max_msgs": 48, "dense_bytes": dpk.dense_bytes,
            "packed_bytes": dpk.packed_bytes,
            "ratio": round(dpk.ratio, 2)}
    except Exception as e:           # sizing is advisory, never fatal
        RESULT["defect_pack"] = {"error": str(e)}
    # second timed run on the same engine: separates machine noise from
    # real throughput (VERDICT r3 item 8 asked the r2->r3 CPU drop be
    # explained with two runs; the identified cause — the CP06 header
    # columns widening EVERY model's m_hdr plane 9 -> 11 — is fixed by
    # the per-codec NHDR, see models/vsr.py)
    if time.time() < DEADLINE - 60 and res.error is None:
        res2 = runner(max_seconds=max(30.0, DEADLINE - time.time()))
        RESULT["run2_distinct_per_s"] = round(
            res2.distinct_states / res2.elapsed, 1)
    # the headline run's dispatch window (fused = 1 dispatch, chunked
    # = the engine default); compare_bench treats depth mismatches
    # between rounds as advisory
    RESULT["pipeline_depth"] = (res.metrics or {}).get(
        "gauges", {}).get("pipeline_depth")
    # level-kernel commit mode + occupancy (ISSUE 10): compare_bench
    # treats commit mismatches between docs as advisory (like pipeline
    # depth) and gates occupancy regressions
    RESULT["commit"] = (res.metrics or {}).get(
        "gauges", {}).get("commit_mode")
    RESULT["occupancy"] = (res.metrics or {}).get(
        "gauges", {}).get("occupancy")
    RESULT["inserts_per_tile"] = (res.metrics or {}).get(
        "gauges", {}).get("inserts_per_tile")
    # symmetry reduction identity (ISSUE 11): the group order the
    # headline run canonicalized by (1 = off — the shipped cfg
    # declares SYMMETRY, so the device default is on) and the
    # generated/distinct-after-canon ratio; compare_bench gates
    # orbit_ratio drops and distinct growth at matching modes
    RESULT["symmetry_perms"] = g.get("symmetry_perms")
    RESULT["orbit_ratio"] = g.get("orbit_ratio")
    # bounds pre-pass identity (ISSUE 13): pack bits saved by interval
    # tightening (1.0 = untightened/off) and the static state bound;
    # compare_bench treats ratio mismatches between docs as advisory,
    # like pipeline depth
    RESULT["bound_tightening_ratio"] = g.get("bound_tightening_ratio")
    RESULT["state_bound"] = g.get("state_bound")
    # A/B the chunked engine's dispatch window on the same probe
    # (ISSUE 4 acceptance): -pipeline 1 vs -pipeline 2 must explore
    # the identical space; the throughput delta is the window's win
    if time.time() < DEADLINE - 240 and res.error is None:
        RESULT["phase"] = "pipeline-ab"
        try:
            ab = {}
            for K in (1, 2):
                e = DeviceBFS(spec, tile_size=tile,
                              fpset_capacity=1 << 21,
                              next_capacity=1 << 15, expand_mult=2,
                              expand_mults={"ReceiveMatchingSVC": 4,
                                            "SendDVC": 4},
                              pipeline=K)
                e.run(max_depth=6)      # compile + warm
                r = e.run(max_seconds=max(30.0,
                                          DEADLINE - time.time()))
                ab[f"pipeline{K}"] = {
                    "distinct": r.distinct_states,
                    "generated": r.states_generated,
                    "distinct_per_s": round(
                        r.distinct_states / r.elapsed, 1),
                    "elapsed_s": round(r.elapsed, 2),
                    "reached_fixpoint": r.error is None,
                    "overlap_saved_s": r.metrics["gauges"].get(
                        "overlap_saved_s"),
                }
            # counts are only comparable when neither run was cut by
            # the time budget (the K=2 run starts later and gets a
            # strictly smaller budget; truncation differences are not
            # a semantics violation) — None = not comparable
            ab["counts_identical"] = (
                ab["pipeline1"]["distinct"]
                == ab["pipeline2"]["distinct"]
                and ab["pipeline1"]["generated"]
                == ab["pipeline2"]["generated"]
            ) if (ab["pipeline1"]["reached_fixpoint"]
                  and ab["pipeline2"]["reached_fixpoint"]) else None
            # cross-level chaining (ISSUE 9 lever 3): the window that
            # SURVIVES level boundaries — the host-round-trip-per-level
            # cost the chunked window still pays disappears
            if time.time() < DEADLINE - 90:
                e = DeviceBFS(spec, tile_size=tile,
                              fpset_capacity=1 << 21,
                              next_capacity=1 << 15, expand_mult=2,
                              expand_mults={"ReceiveMatchingSVC": 4,
                                            "SendDVC": 4},
                              pipeline=2)
                e.run_chained(max_depth=6)      # compile + warm
                r = e.run_chained(max_seconds=max(
                    30.0, DEADLINE - time.time()))
                ab["chained"] = {
                    "distinct": r.distinct_states,
                    "generated": r.states_generated,
                    "distinct_per_s": round(
                        r.distinct_states / r.elapsed, 1),
                    "elapsed_s": round(r.elapsed, 2),
                    "reached_fixpoint": r.error is None,
                }
                if ab["chained"]["reached_fixpoint"] and \
                        ab["counts_identical"]:
                    ab["counts_identical"] = (
                        ab["chained"]["distinct"]
                        == ab["pipeline1"]["distinct"]
                        and ab["chained"]["generated"]
                        == ab["pipeline1"]["generated"])
            # commit-mode A/B (ISSUE 10 acceptance spot-check): the
            # occupancy-packed fused commit vs the historical
            # per-action serial phases — counts must be IDENTICAL,
            # the throughput delta is the tentpole's win
            if time.time() < DEADLINE - 90:
                e = DeviceBFS(spec, tile_size=tile,
                              fpset_capacity=1 << 21,
                              next_capacity=1 << 15, expand_mult=2,
                              expand_mults={"ReceiveMatchingSVC": 4,
                                            "SendDVC": 4},
                              pipeline=2, commit="per-action")
                e.run(max_depth=6)      # compile + warm
                r = e.run(max_seconds=max(30.0,
                                          DEADLINE - time.time()))
                m = (r.metrics or {}).get("gauges", {})
                ab["per_action_commit"] = {
                    "distinct": r.distinct_states,
                    "generated": r.states_generated,
                    "distinct_per_s": round(
                        r.distinct_states / r.elapsed, 1),
                    "elapsed_s": round(r.elapsed, 2),
                    "reached_fixpoint": r.error is None,
                    "occupancy": m.get("occupancy"),
                    "inserts_per_tile": m.get("inserts_per_tile"),
                }
                if ab["per_action_commit"]["reached_fixpoint"] and \
                        ab["counts_identical"]:
                    ab["counts_identical"] = (
                        ab["per_action_commit"]["distinct"]
                        == ab["pipeline1"]["distinct"]
                        and ab["per_action_commit"]["generated"]
                        == ab["pipeline1"]["generated"])
            # symmetry A/B (ISSUE 11 acceptance): the shipped cfg
            # declares SYMMETRY, so the headline already runs
            # orbit-canonical; the off leg measures how many distinct
            # states the reduction is folding away.  Counts are NOT
            # expected to match — the ratio IS the result (bounded by
            # wall clock: the unreduced space can be |Values|! larger)
            if time.time() < DEADLINE - 120:
                e = DeviceBFS(spec, tile_size=tile,
                              fpset_capacity=1 << 21,
                              next_capacity=1 << 15, expand_mult=2,
                              expand_mults={"ReceiveMatchingSVC": 4,
                                            "SendDVC": 4},
                              symmetry=False)
                e.run(max_depth=6)      # compile + warm
                r = e.run(max_seconds=max(
                    30.0, min(DEADLINE - time.time(), 300.0)))
                on = ab["pipeline1"]
                ab["symmetry_off"] = {
                    "distinct": r.distinct_states,
                    "generated": r.states_generated,
                    "distinct_per_s": round(
                        r.distinct_states / r.elapsed, 1),
                    "reached_fixpoint": r.error is None,
                    "orbit_cut": (round(r.distinct_states
                                        / on["distinct"], 3)
                                  if r.error is None
                                  and on["reached_fixpoint"]
                                  else None),
                }
            # bounds A/B (ISSUE 13 acceptance): declared-widths
            # packing + full action lists vs the tightened default —
            # counts must be IDENTICAL (the facts only change the
            # representation, never the explored space); the
            # bound_tightening_ratio is the static win
            if time.time() < DEADLINE - 90:
                e = DeviceBFS(spec, tile_size=tile,
                              fpset_capacity=1 << 21,
                              next_capacity=1 << 15, expand_mult=2,
                              expand_mults={"ReceiveMatchingSVC": 4,
                                            "SendDVC": 4},
                              pipeline=2, bounds=False)
                e.run(max_depth=6)      # compile + warm
                r = e.run(max_seconds=max(30.0,
                                          DEADLINE - time.time()))
                ab["bounds_off"] = {
                    "distinct": r.distinct_states,
                    "generated": r.states_generated,
                    "distinct_per_s": round(
                        r.distinct_states / r.elapsed, 1),
                    "elapsed_s": round(r.elapsed, 2),
                    "reached_fixpoint": r.error is None,
                }
                if ab["bounds_off"]["reached_fixpoint"] and \
                        ab["counts_identical"]:
                    ab["counts_identical"] = (
                        ab["bounds_off"]["distinct"]
                        == ab["pipeline1"]["distinct"]
                        and ab["bounds_off"]["generated"]
                        == ab["pipeline1"]["generated"])
            # POR A/B (ISSUE 16 acceptance): the ample-set filter
            # consumes speclint pass 7's independence facts inside the
            # fused commit.  The VERDICT must be identical to the
            # unreduced run, but distinct/generated may legitimately
            # SHRINK, so this leg is deliberately NOT folded into
            # counts_identical; por_cut_ratio (kept/full successor
            # work, 1.0 = filter inert on this spec) is the measured
            # win
            if time.time() < DEADLINE - 90:
                e = DeviceBFS(spec, tile_size=tile,
                              fpset_capacity=1 << 21,
                              next_capacity=1 << 15, expand_mult=2,
                              expand_mults={"ReceiveMatchingSVC": 4,
                                            "SendDVC": 4},
                              pipeline=2, por="on")
                e.run(max_depth=6)      # compile + warm
                r = e.run(max_seconds=max(30.0,
                                          DEADLINE - time.time()))
                m = (r.metrics or {}).get("gauges", {})
                ab["por_on"] = {
                    "distinct": r.distinct_states,
                    "generated": r.states_generated,
                    "distinct_per_s": round(
                        r.distinct_states / r.elapsed, 1),
                    "elapsed_s": round(r.elapsed, 2),
                    "reached_fixpoint": r.error is None,
                    "por_cut_ratio": m.get("por_cut_ratio"),
                    "ample_states": m.get("ample_states"),
                    "por_eligible_actions": m.get(
                        "por_eligible_actions"),
                    "distinct_shrunk_or_equal": (
                        r.distinct_states
                        <= ab["pipeline1"]["distinct"]
                        if r.error is None
                        and ab["pipeline1"]["reached_fixpoint"]
                        else None),
                    "verdict_identical": (
                        r.ok == res.ok
                        and r.violated_invariant
                        == res.violated_invariant
                        if r.error is None else None),
                }
                RESULT["por_cut_ratio"] = m.get("por_cut_ratio")
                RESULT["por_eligible_actions"] = m.get(
                    "por_eligible_actions")
            RESULT["pipeline_ab"] = ab
            print(f"bench: pipeline A/B "
                  f"{ab['pipeline1']['distinct_per_s']} -> "
                  f"{ab['pipeline2']['distinct_per_s']} distinct/s"
                  + (f" -> chained "
                     f"{ab['chained']['distinct_per_s']}"
                     if "chained" in ab else "")
                  + f", counts_identical={ab['counts_identical']}",
                  file=sys.stderr)
        except Exception as e:  # noqa: BLE001 — A/B never kills bench
            RESULT["pipeline_ab"] = {
                "error": f"{type(e).__name__}: {e}"}
        RESULT["phase"] = "done"
    RESULT["perf_gate"] = _perf_gate(RESULT)
    if RESULT["perf_gate"].get("ok") is False:
        print(f"bench: PERF GATE FAILED vs "
              f"{RESULT['perf_gate']['baseline']}: "
              f"{RESULT['perf_gate']['detail']}", file=sys.stderr)
    RESULT["regression_note"] = (
        "r2->r3 CPU headline dropped 8399->6564 distinct/s because r3 "
        "widened the shared message-header plane from 9 to 11 columns "
        "for CP06's flag/cp fields, growing every model's hashed bytes "
        "per slot; r4 makes the width per-codec (NHDR=9 again for "
        "VSR/A01/I01/ST03/AS04/RR05/AL05, 11 only for CP06)")
    # attach measured round artifacts (each records its own backend):
    # guided-hunt time-to-violation (scripts/defect_hunt.py),
    # configs[2]-scale simulation throughput (scripts/sim_scale.py),
    # paged defect-config BFS window (scripts/defect_bfs_window.py),
    # hunt sampling-mode ablation (scripts/hunt_ablation.py), and the
    # device-vs-interpreter liveness graph build
    # (scripts/liveness_speedup.py)
    # plus the recorded live-TPU artifacts (bench_tpu_run.json is a
    # full bench run captured while the flapping axon tunnel was up;
    # tpu_tests.json is the TPU-backend differential-suite status) so a
    # cpu-fallback end-of-round run still carries the real-TPU numbers
    _attach_and_lift()
    print(f"bench: device {res.distinct_states} distinct "
          f"({res.error or 'fixpoint'}), {dev_sps:.0f} generated/s, "
          f"{distinct_sps:.0f} distinct/s, diameter {res.diameter}",
          file=sys.stderr)
    emit(None)


def _attach_and_lift():
    """Attach the recorded round artifacts and lift their headline
    numbers to the round-doc top level (shared by the reference
    headline and the reference-absent stub round)."""
    for key, fname in ATTACHMENTS:
        p = os.path.join(REPO, "scripts", fname)
        if os.path.exists(p):
            try:
                with open(p) as f:
                    loaded = json.load(f)
            except ValueError:
                continue
            if key == "tpu_run":
                # a captured full bench run carries its own attachments;
                # strip them so re-capturing stdout back to
                # bench_tpu_run.json can never nest runs recursively
                for k, _f in ATTACHMENTS:
                    loaded.pop(k, None)
            RESULT[key] = loaded
    # walker-fleet simulation headline (ISSUE 7): walkers / walks/s /
    # split mode of the fleet-rebuilt sim_scale probe lifted to the
    # round-doc top level, so scripts/compare_bench.py's walks/s gate
    # diffs rounds directly (cross-walker-count drops are advisory)
    sc = RESULT.get("sim_scale")
    if isinstance(sc, dict) and sc.get("walks_per_s") is not None:
        RESULT["sim_walkers"] = sc.get("walkers")
        RESULT["sim_walks_per_s"] = sc.get("walks_per_s")
        RESULT["sim_split_enabled"] = bool(sc.get("split_enabled"))
    # batched trace validation headline (ISSUE 8): traces/s, round
    # size and divergence-localization health of the validate_demo
    # drill lifted to the round-doc top level, so compare_bench's
    # traces/s gate diffs rounds directly (cross-backend/batch drops
    # are advisory)
    vd = RESULT.get("validate_demo")
    if isinstance(vd, dict) and vd.get("traces_per_s") is not None:
        RESULT["validate_traces_per_s"] = vd.get("traces_per_s")
        RESULT["validate_batch"] = vd.get("batch")
        RESULT["validate_traces"] = vd.get("traces")
        RESULT["validate_ok"] = bool(vd.get("ok"))
    # streamed liveness headline (ISSUE 15): edge count, emission
    # rate and graph-construction overhead of the liveness_speedup
    # A/B's largest pin lifted to the round-doc top level, so
    # scripts/compare_bench.py's gate_liveness diffs rounds directly
    # (streamed-vs-two-pass mode mismatches are advisory)
    ls = RESULT.get("liveness_speedup")
    if isinstance(ls, dict) and ls.get("edges_per_s") is not None:
        RESULT["edges"] = ls.get("edges")
        RESULT["edges_per_s"] = ls.get("edges_per_s")
        RESULT["graph_overhead_ratio"] = ls.get(
            "graph_overhead_ratio")
        RESULT["liveness_check_s"] = ls.get("check_s")
        RESULT["liveness_mode"] = ls.get("mode")
    hr = RESULT.get("defect_hunt")
    if isinstance(hr, dict) and hr.get("split_enabled") is not None:
        RESULT["hunt_split_enabled"] = bool(hr.get("split_enabled"))
        RESULT["hunt_time_to_violation_s"] = hr.get(
            "time_to_violation_s")
    # headline the defect-scale number when a TPU window ran (the r4
    # verdict's graded target: >= 10x the CPU window's 1,160 distinct/s)
    dw = RESULT.get("defect_bfs_window")
    if isinstance(dw, dict) and not str(dw.get("backend", "")).startswith(
            "cpu"):
        RESULT["defect_tpu_distinct_per_s"] = dw.get("distinct_per_s")
        RESULT["defect_tpu_vs_cpu_window"] = dw.get("vs_cpu_window_1160")
    _embed_telemetry()
    _embed_spool()


def _embed_telemetry():
    """Embed a tpuvsr-telemetry/1 snapshot in the round doc
    (ISSUE 17): run one stub job through a throwaway service spool,
    fold its journals with the streamed aggregator, and record the
    fleet-level series (queue-wait/run-time histograms, per-window
    rates, worker utilization) next to the engine headline — every
    BENCH_r*.json from r07 on carries them, and compare_bench's
    ``gate_telemetry`` fold-determinism drill activates on rounds
    that do.  Since ISSUE 18 the drill also exercises the serving
    guard, so the round doc records ``rate_limited`` /
    ``breaker_trips`` counters and the measured fast-fail rate
    (``guard_reject_per_s``, gated by ``gate_guard``)."""
    import shutil
    import tempfile
    tmp = tempfile.mkdtemp(prefix="tpuvsr-bench-telemetry-")
    try:
        from tpuvsr.obs.telemetry import TelemetryAggregator
        from tpuvsr.service.queue import JobQueue
        from tpuvsr.service.worker import Worker
        q = JobQueue(os.path.join(tmp, "spool"))
        q.submit("<stub>", engine="device", tenant="bench",
                 flags={"stub": True})
        Worker(q, devices=1).drain()
        # guard drill (ISSUE 18): fold one throttled tenant and one
        # breaker trip into the round doc, and time the fast-fail
        # path — rejections/sec is serving-tier health (a slow
        # rejector turns the rate limiter into a DoS amplifier);
        # scripts/compare_bench.py's gate_guard diffs it between
        # rounds at matching limiter configs
        from tpuvsr.serve.guard import Guard, GuardDenied, spec_digest
        guard = Guard(q.spool, rate=0.001, burst=1.0, breaker_k=1)
        RESULT["guard_limiter"] = {"rate": 0.001, "burst": 1.0,
                                   "breaker_k": 1}
        denials = 0
        t0g = time.time()
        for _ in range(200):
            try:
                guard.admit_submission("bench", ts=time.time())
            except GuardDenied:
                denials += 1
        reject_s = time.time() - t0g
        guard.breaker_record("bench", spec_digest("<stub>", None),
                             False, ts=time.time())
        agg = TelemetryAggregator(q.spool, journal_breaches=False)
        agg.poll()
        snap = agg.snapshot()
        RESULT["telemetry"] = snap
        g = snap.get("guard") or {}
        RESULT["rate_limited"] = g.get("rate_limited")
        RESULT["breaker_trips"] = g.get("breaker_trips")
        RESULT["guard_reject_per_s"] = round(
            denials / max(reject_s, 1e-9), 1)
    except Exception as e:  # noqa: BLE001 — the embed never kills bench
        RESULT["telemetry"] = {"error": f"{type(e).__name__}: {e}"}
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _embed_spool():
    """Embed spool-driver op rates in the round doc (ISSUE 20): time
    record appends, claim/release cycles and a full-stream fold on a
    throwaway spool for each driver (fs / objstore / quorum), so
    rounds carry the data plane's control-path cost next to the
    engine headline.  ``scripts/compare_bench.py``'s ``gate_spool``
    diffs the rates between rounds at MATCHING drivers; cross-driver
    spreads (quorum pays W-replica fsyncs per append) are expected
    and advisory only."""
    import shutil
    import tempfile
    out = {}
    n_app, n_claim = 256, 64
    for name in ("fs", "objstore", "quorum"):
        tmp = tempfile.mkdtemp(prefix=f"tpuvsr-bench-spool-{name}-")
        try:
            from tpuvsr.service.spooldrv import open_driver
            drv = open_driver(os.path.join(tmp, "spool"), driver=name)
            t0 = time.time()
            for i in range(n_app):
                drv.append("bench", {"op": "tick", "i": i})
            t_app = time.time() - t0
            t0 = time.time()
            for i in range(n_claim):
                drv.try_claim(f"j{i:04d}", owner="bench", epoch=1)
                drv.release_claim(f"j{i:04d}", epoch=1)
            t_claim = time.time() - t0
            t0 = time.time()
            recs, _ = drv.read("bench", None)
            t_fold = time.time() - t0
            out[name] = {
                "appends_per_s": round(n_app / max(t_app, 1e-9), 1),
                "claims_per_s": round(n_claim / max(t_claim, 1e-9), 1),
                "fold_ms": round(t_fold * 1000.0, 2),
                "records_folded": len(recs),
            }
        except Exception as e:  # noqa: BLE001 — never kills bench
            out[name] = {"error": f"{type(e).__name__}: {e}"}
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
    RESULT["spool"] = out


def _stub_round(reason):
    """Reference-absent fallback round (ISSUE 16): the VSR.tla
    headline needs the reference corpus, but the POR / symmetry /
    bounds levers are all measurable on the in-repo stub fixtures
    (tpuvsr.testing) — the independent-counters fixture (16 states,
    two invisible independent actions: the POR oracle) and the
    SymPair fixture (16 states / 5 orbits: the symmetry oracle).

    The throughput numbers here are honestly useless (tiny spaces,
    compile-dominated — the perf gate is skipped and the round's
    headline ``value`` is the POR cut ratio, not states/sec), but the
    CUT RATIOS and the count/verdict identities are exact and
    machine-checked, which is what the r06 measurement debt asked
    for."""
    import tpuvsr.testing as T
    from tpuvsr.engine.bfs import bfs_check

    RESULT["metric"] = ("stub-fixture lever A/B: POR cut ratio / "
                        "symmetry orbit cut / bounds identity "
                        "(reference corpus absent)")
    RESULT["unit"] = "generated-kept / generated-full"
    RESULT["reference_absent"] = reason
    RESULT["mode"] = "fused"

    def leg(e):
        e.run(max_depth=1)          # compile + warm
        r = e.run(max_seconds=max(30.0, DEADLINE - time.time()))
        g = (r.metrics or {}).get("gauges", {})
        return r, g, {
            "distinct": r.distinct_states,
            "generated": r.states_generated,
            "diameter": r.diameter,
            "elapsed_s": round(r.elapsed, 3),
            "error": r.error,
            # a deadlocked fixpoint is still a completed exploration
            "completed": r.error in (None, "deadlock"),
        }

    def verdict(r):
        return (r.ok, r.violated_invariant, r.error)

    ab = {}
    # --- POR A/B on the independent-counters fixture (the ISSUE 16
    # acceptance oracle: cut ratio < 1.0, counts shrink, verdict and
    # the (3,3) deadlock identical) --------------------------------
    RESULT["phase"] = "stub-por-ab"
    spec = T.counter_spec(inv_free=True)
    interp = bfs_check(spec)
    RESULT["baseline_interp_distinct_per_s"] = round(
        interp.distinct_states / max(interp.elapsed, 1e-9), 1)
    r_off, _g_off, ab["counter_por_off"] = leg(
        T.stub_device_engine(spec=spec, por="off"))
    r_on, g_on, ab["counter_por_on"] = leg(
        T.stub_device_engine(spec=spec, por="on"))
    ab["counter_por_on"].update({
        "por_cut_ratio": g_on.get("por_cut_ratio"),
        "ample_states": g_on.get("ample_states"),
        "por_eligible_actions": g_on.get("por_eligible_actions"),
    })
    ab["counter_interp_matches_off"] = (
        interp.distinct_states == r_off.distinct_states)
    ab["counter_verdict_identical"] = verdict(r_off) == verdict(r_on)
    ab["counter_distinct_shrunk_or_equal"] = (
        r_on.distinct_states <= r_off.distinct_states)
    cut = g_on.get("por_cut_ratio")
    # the POR-on metrics document carries the por gauges — it IS the
    # round's diffable metrics doc (scripts/compare_bench.py gate_por)
    RESULT["metrics"] = r_on.metrics
    RESULT["por_cut_ratio"] = cut
    RESULT["por_eligible_actions"] = g_on.get("por_eligible_actions")
    RESULT["value"] = cut if cut is not None else 0.0
    RESULT["vs_baseline"] = (
        round(r_off.states_generated
              / max(1, r_on.states_generated), 3))

    # --- POR A/B on the symmetric fixture (symmetry OFF so the cut
    # is attributable to the ample filter alone) -------------------
    r_soff, _gs, ab["sympair_por_off"] = leg(
        T.stub_sym_engine(symmetry=False, por="off"))
    r_son, g_son, ab["sympair_por_on"] = leg(
        T.stub_sym_engine(symmetry=False, por="on"))
    ab["sympair_por_on"]["por_cut_ratio"] = g_son.get("por_cut_ratio")
    ab["sympair_verdict_identical"] = (
        verdict(r_soff) == verdict(r_son))

    # --- symmetry lever (ISSUE 11) on the same fixture ------------
    RESULT["phase"] = "stub-symmetry-ab"
    r_sym, g_sym, ab["sympair_symmetry_on"] = leg(
        T.stub_sym_engine(symmetry="auto"))
    ab["sympair_symmetry_on"]["orbit_cut"] = round(
        r_soff.distinct_states / max(1, r_sym.distinct_states), 3)
    RESULT["symmetry_perms"] = g_sym.get("symmetry_perms")
    RESULT["orbit_ratio"] = g_sym.get("orbit_ratio")

    # --- composed: symmetry + bounds + POR on one engine (the
    # acceptance composition: verdicts must survive the stack) -----
    RESULT["phase"] = "stub-composed"
    try:
        r_comp, g_comp, ab["sympair_composed"] = leg(
            T.stub_sym_engine(symmetry="auto", por="on", bounds=True))
        ab["sympair_composed"].update({
            "por_cut_ratio": g_comp.get("por_cut_ratio"),
            "verdict_identical": (
                (r_comp.ok, r_comp.violated_invariant)
                == (r_sym.ok, r_sym.violated_invariant)),
        })
    except Exception as e:  # noqa: BLE001 — a leg never kills bench
        ab["sympair_composed"] = {"error": f"{type(e).__name__}: {e}"}

    # --- bounds lever (ISSUE 13) on the dead-action fixture:
    # pruned vs carried dead lane must be bit-identical -------------
    RESULT["phase"] = "stub-bounds-ab"
    try:
        rb_on, gb_on, ab["counter_bounds_on"] = leg(
            T.stub_device_engine(dead_action=True, bounds=True))
        rb_off, _gb, ab["counter_bounds_off"] = leg(
            T.stub_device_engine(dead_action=True, bounds=False))
        ab["bounds_counts_identical"] = (
            rb_on.distinct_states == rb_off.distinct_states
            and rb_on.states_generated == rb_off.states_generated)
        RESULT["bound_tightening_ratio"] = gb_on.get(
            "bound_tightening_ratio")
        RESULT["state_bound"] = gb_on.get("state_bound")
    except Exception as e:  # noqa: BLE001
        ab["counter_bounds_on"] = {"error": f"{type(e).__name__}: {e}"}

    # --- projection onto the recorded deep pins: what the measured
    # cut ratio would buy rr05_deep / shipped_pin IF those specs
    # admit the same reduction.  A projection, not a measurement —
    # corpus eligibility must be read off a reference mount via
    # `scripts/lint_corpus.py --independence` ----------------------
    proj = {}
    if cut:
        for name in ("rr05_deep", "shipped_pin"):
            p = os.path.join(REPO, "scripts", f"{name}.json")
            try:
                with open(p) as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                continue
            if not doc.get("distinct_per_s"):
                continue
            proj[name] = {
                "recorded_distinct_per_s": doc.get("distinct_per_s"),
                "recorded_depth": doc.get("depth_reached"),
                "recorded_fixpoint": doc.get("fixpoint"),
                "stub_cut_ratio": cut,
                "projected_effective_distinct_per_s": round(
                    doc["distinct_per_s"] / cut, 1),
                "note": ("projection from the stub-measured cut "
                         "ratio; an upper bound that holds only if "
                         "the spec's actions are as independent and "
                         "invisible as the stub's"),
            }
    RESULT["por_projection"] = proj or None

    RESULT["phase"] = "done (stub-fixture round; reference absent)"
    RESULT["pipeline_ab"] = ab
    RESULT["perf_gate"] = {
        "skipped": "stub-fixture round — the headline value is a cut "
                   "ratio, not comparable to the reference VSR.tla "
                   "rounds"}
    _attach_and_lift()
    print(f"bench: stub round por_cut_ratio={cut} (counters "
          f"{ab['counter_por_off']['distinct']} -> "
          f"{ab['counter_por_on']['distinct']} distinct, sympair "
          f"cut={g_son.get('por_cut_ratio')}, orbit_cut="
          f"{ab['sympair_symmetry_on']['orbit_cut']})",
          file=sys.stderr)
    emit(None)


if __name__ == "__main__":
    # registered here, not at import: bench_capture.py imports this
    # module for ATTACHMENTS and must keep its own signal behavior
    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    try:
        main()
    except BaseException as e:  # noqa: BLE001 — always emit the JSON
        RESULT["phase"] += f" (error: {type(e).__name__}: {e})"
        import traceback
        traceback.print_exc()
        emit(1)
    emit(0)
