"""Headline benchmark: distinct states/sec of the device BFS engine on
the shrunken flagship config (BASELINE.json configs[0]: VSR.tla with
R=3, C=1, Values={v1}, StartViewOnTimerLimit=1 — 43,941 distinct
states, diameter 24).

Prints ONE JSON line {metric, value, unit, vs_baseline}.
vs_baseline = device states/sec over the single-thread interpreter
oracle's states/sec on the same spec (the stand-in for the reference's
explicit-state checker until a TLC number is recorded; the reference
publishes no throughput figures — SURVEY.md §6).

Robustness: the session TPU is reached through a tunnel that can hang
backend init; the platform is probed in a subprocess with a timeout and
the bench falls back to CPU if the tunnel is down.
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)
REFERENCE = os.environ.get(
    "TPUVSR_REFERENCE", "/root/reference/vsr-revisited/paper")

BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", "2400"))
INTERP_STATES = int(os.environ.get("BENCH_INTERP_STATES", "4000"))


def _probe_default_backend(timeout=180):
    """Can the session's default JAX platform initialize?  Run the probe
    in a subprocess: a dead TPU tunnel hangs backend init forever."""
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.default_backend())"],
            capture_output=True, text=True, timeout=timeout)
        if r.returncode == 0:
            return r.stdout.strip().splitlines()[-1]
    except subprocess.TimeoutExpired:
        pass
    return None


def main():
    backend = _probe_default_backend()
    if backend is None:
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        backend = "cpu (tpu tunnel unavailable)"
    import jax
    if backend.startswith("cpu"):
        jax.config.update("jax_platforms", "cpu")
    print(f"bench: backend = {backend}", file=sys.stderr)

    from __graft_entry__ import _small_spec
    from tpuvsr.engine.bfs import bfs_check
    from tpuvsr.engine.device_bfs import DeviceBFS

    # baseline: single-thread interpreter (exact TLC-style enumeration)
    spec = _small_spec()
    base = bfs_check(spec, max_states=INTERP_STATES)
    base_sps = base.states_generated / base.elapsed
    print(f"bench: interpreter baseline {base_sps:.0f} generated/s",
          file=sys.stderr)

    # device engine: warm up the jits on a depth-limited run, then
    # measure on the SAME instance (run() resets its store/FPSet, and
    # jax.jit caches by closure identity, so the compile is reused)
    tile = int(os.environ.get("BENCH_TILE", "64"))
    eng = DeviceBFS(spec, tile_size=tile)
    t0 = time.time()
    eng.run(max_depth=1)
    print(f"bench: compile+warmup {time.time() - t0:.1f}s",
          file=sys.stderr)

    res = eng.run(max_seconds=BUDGET_S,
                  log=lambda m: print(f"bench: {m}", file=sys.stderr))
    dev_sps = res.states_generated / res.elapsed
    distinct_sps = res.distinct_states / res.elapsed
    print(f"bench: device {res.distinct_states} distinct "
          f"({res.error or 'fixpoint'}), {dev_sps:.0f} generated/s, "
          f"{distinct_sps:.0f} distinct/s, diameter {res.diameter}",
          file=sys.stderr)

    print(json.dumps({
        "metric": "VSR.tla BFS distinct states/sec "
                  "(R=3, |Values|=1, timer=1)",
        "value": round(distinct_sps, 1),
        "unit": "states/sec",
        "vs_baseline": round(dev_sps / base_sps, 3),
    }))


if __name__ == "__main__":
    main()
